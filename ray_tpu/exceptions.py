"""User-facing exceptions (reference: python/ray/exceptions.py)."""
from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get()` on the caller.

    Carries the remote traceback string (reference: RayTaskError,
    python/ray/exceptions.py)."""

    def __init__(self, cause_cls_name: str, cause: Optional[BaseException], tb_str: str, task_name: str = ""):
        self.cause = cause
        self.cause_cls_name = cause_cls_name
        self.tb_str = tb_str
        self.task_name = task_name
        super().__init__(f"task {task_name!r} failed with {cause_cls_name}:\n{tb_str}")

    @classmethod
    def from_exception(cls, e: BaseException, task_name: str = "") -> "TaskError":
        return cls(type(e).__name__, e, traceback.format_exc(), task_name)

    def __reduce__(self):
        # The cause itself may not be picklable; ship the name + traceback.
        return (TaskError, (self.cause_cls_name, None, self.tb_str, self.task_name))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or out of restarts)."""

    def __init__(self, msg="the actor is dead"):
        super().__init__(msg)


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unavailable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(timeout=...)` expired."""


class RpcTimeoutError(RayTpuError, TimeoutError):
    """A control-plane RPC exceeded its deadline (the reply never arrived
    within the transport's budget — lost frame, dead peer, or a wedged
    head).  Distinct from :class:`GetTimeoutError`: that one means the
    *object* wasn't ready in time; this one means the *channel* gave no
    answer at all, so retries/failover are the right reaction."""

    def __init__(self, op: str = "", elapsed: float = 0.0,
                 timeout: Optional[float] = None, attempts: int = 1):
        self.op = op
        self.elapsed = elapsed
        self.timeout = timeout
        self.attempts = attempts
        bound = f"{timeout:.3f}s" if timeout is not None else "unbounded"
        super().__init__(
            f"RPC {op!r} got no reply within its deadline "
            f"(elapsed {elapsed:.3f}s, budget {bound}, "
            f"{attempts} attempt(s))")

    def __reduce__(self):
        return (RpcTimeoutError,
                (self.op, self.elapsed, self.timeout, self.attempts))


class HeadConnectionError(RayTpuError, ConnectionError):
    """Connecting/registering with the head failed.  Carries the head
    address, how long we tried, and whether the TCP socket ever connected
    (separates "nothing is listening" from "the head accepted the socket
    but never completed registration")."""

    def __init__(self, address: str, elapsed: float,
                 socket_connected: bool, detail: str = ""):
        self.address = address
        self.elapsed = elapsed
        self.socket_connected = socket_connected
        phase = ("socket connected but registration never completed"
                 if socket_connected else "TCP connection failed")
        msg = (f"could not join head at {address}: {phase} "
               f"after {elapsed:.1f}s")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)

    def __reduce__(self):
        return (HeadConnectionError,
                (self.address, self.elapsed, self.socket_connected))


class ObjectStoreFullError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Worker was killed by the memory monitor under host memory pressure
    and the task's retry budget is exhausted (reference:
    src/ray/common/memory_monitor.h:52 + worker_killing_policy.h:33)."""


class PlacementGroupSchedulingError(RayTpuError):
    """Placement group could not be reserved (infeasible or timeout)."""


class BatcherClosedError(RayTpuError):
    """A @serve.batch batcher was closed (deployment teardown /
    serve.shutdown) while this call was queued or before it was
    submitted — the request was never handed to the handler."""


class EngineClosedError(RayTpuError):
    """The serve LLM decode engine was closed (replica drain / fatal
    engine error) with this request still pending or in flight."""


class KVPoolExhaustedError(RayTpuError):
    """The engine's paged KV cache cannot hold this request: it needs
    more pages than the pool's capacity (or the pool is exhausted with
    nothing left to preempt).  Raise max_ctx/num_pages or shorten the
    request."""


class CrossMeshTransferError(RayTpuError):
    """Device-array transfer between meshes failed (ray_tpu.parallel)."""


class MeshGroupError(RayTpuError):
    """The SPMD gang is poisoned: one or more mesh ranks died (or timed
    out) while a collective fan-out was in flight.  Because every rank of
    a ``MeshGroup`` participates in one ``jax.distributed`` world, a single
    dead rank invalidates the *whole group* — surviving ranks may be
    blocked forever inside a collective — so the supervisor raises this
    eagerly instead of letting ``get()`` hang on the poisoned peers.

    ``failed_ranks`` maps rank -> the underlying per-rank exception (an
    ``ActorDiedError``/``WorkerCrashedError``/``TaskError``...).
    ``restarts`` records how many gang restarts had been consumed when the
    error was raised (useful when the restart budget is exhausted)."""

    def __init__(self, msg: str = "mesh group failed",
                 failed_ranks: Optional[dict] = None, restarts: int = 0):
        self.failed_ranks = dict(failed_ranks or {})
        self.restarts = restarts
        self._base_msg = msg
        if self.failed_ranks:
            detail = ", ".join(
                f"rank {r}: {type(e).__name__}" if isinstance(e, BaseException)
                else f"rank {r}: {e}"
                for r, e in sorted(self.failed_ranks.items()))
            msg = f"{msg} (failed ranks: {detail})"
        super().__init__(msg)

    def __reduce__(self):
        # Per-rank causes may not be picklable; ship their string forms.
        flat = {r: (str(e) if isinstance(e, BaseException) else e)
                for r, e in self.failed_ranks.items()}
        return (MeshGroupError, (self._base_msg, flat, self.restarts))


# Aliases matching the reference's names so ported user code reads naturally.
RayError = RayTpuError
RayTaskError = TaskError
RayActorError = ActorDiedError
