"""Worker process entry point (reference: python/ray/_private/workers/
default_worker.py + CoreWorkerProcess::RunTaskExecutionLoop,
src/ray/core_worker/core_worker_process.cc:63).

A reader thread receives messages from the head and routes request-replies to
futures and task specs to an execution queue; the main thread (plus a thread
pool for max_concurrency>1 actors) executes tasks.
"""
from __future__ import annotations

import os
import queue
import sys
import threading
from concurrent.futures import ThreadPoolExecutor
from multiprocessing.connection import Client

from ray_tpu._private.ids import JobID, NodeID, WorkerID
from ray_tpu._private.task_spec import TaskSpec, TaskType
from ray_tpu._private.worker import ConnTransport, CoreWorker, set_global_worker


def main():
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])

    head_addr = os.environ.get("RAY_TPU_HEAD_ADDR")
    if head_addr:  # worker on a remote node: TCP to the head
        host, port = head_addr.rsplit(":", 1)
        conn = Client((host, int(port)), family="AF_INET", authkey=authkey)
    else:
        socket_path = os.environ["RAY_TPU_HEAD_SOCKET"]
        conn = Client(socket_path, family="AF_UNIX", authkey=authkey)
    transport = ConnTransport(conn, authkey)
    worker = CoreWorker(worker_id, node_id, JobID.nil(), transport, mode="worker")
    set_global_worker(worker)

    task_queue: "queue.Queue" = queue.Queue()
    stop = threading.Event()

    def reader():
        try:
            while True:
                msg = conn.recv()
                t = msg.get("type")
                if t == "reply":
                    transport.on_reply(msg)
                elif t == "execute":
                    task_queue.put(msg["spec"])
                elif t == "shutdown":
                    stop.set()
                    task_queue.put(None)
                    return
        except (EOFError, OSError):
            stop.set()
            task_queue.put(None)

    threading.Thread(target=reader, name="rtpu-reader", daemon=True).start()
    transport.send({"type": "register", "worker_id": worker_id.binary(),
                    "node_id": node_id.binary(), "pid": os.getpid()})

    pool: ThreadPoolExecutor | None = None

    def run_one(spec: TaskSpec):
        msg = worker.execute_task(spec)
        transport.send(msg)

    while not stop.is_set():
        spec = task_queue.get()
        if spec is None:
            break
        if spec.task_type == TaskType.ACTOR_CREATION and spec.max_concurrency > 1:
            pool = ThreadPoolExecutor(max_workers=spec.max_concurrency,
                                      thread_name_prefix="rtpu-actor")
        if pool is not None and spec.task_type == TaskType.ACTOR_TASK:
            pool.submit(run_one, spec)
        else:
            run_one(spec)

    try:
        conn.close()
    except Exception:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
