"""Worker process entry point (reference: python/ray/_private/workers/
default_worker.py + CoreWorkerProcess::RunTaskExecutionLoop,
src/ray/core_worker/core_worker_process.cc:63).

Two ingress paths feed one execution queue:
  - the head connection (classic dispatch, request replies), and
  - the worker's own direct listener (leased task pushes and actor calls
    from other workers/drivers — reference: the direct task/actor
    transports, core_worker/transport/).
Completions reply on the path the task arrived on: head tasks report
task_done to the head; direct tasks answer the submitting caller, which
owns the results.
"""
from __future__ import annotations

import os
import queue
import sys
import threading
from multiprocessing.connection import Client

from ray_tpu._private.ids import JobID, NodeID, WorkerID
from ray_tpu._private.task_spec import ArgKind, TaskSpec, TaskType
from ray_tpu._private.worker import ConnTransport, CoreWorker, set_global_worker


def _has_ref_args(spec: TaskSpec) -> bool:
    """True when any task argument is an object ref — executing it may
    block the main loop waiting on another task's (possibly buffered)
    completion."""
    return any(a.kind == ArgKind.REF
               for a in list(spec.args) + list(spec.kwargs.values()))


def main():
    import faulthandler
    import signal

    # SIGUSR1 dumps all thread stacks to stderr (lands in the worker's
    # captured log) — the debugging hook for stuck workers.
    try:
        faulthandler.register(signal.SIGUSR1)
    except Exception:
        pass
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])

    head_addr = os.environ.get("RAY_TPU_HEAD_ADDR")
    if head_addr:  # worker on a remote node: TCP to the head
        host, port = head_addr.rsplit(":", 1)
        conn = Client((host, int(port)), family="AF_INET", authkey=authkey)
    else:
        socket_path = os.environ["RAY_TPU_HEAD_SOCKET"]
        conn = Client(socket_path, family="AF_UNIX", authkey=authkey)
    transport = ConnTransport(conn, authkey)
    worker = CoreWorker(worker_id, node_id, JobID.nil(), transport, mode="worker")
    set_global_worker(worker)

    # SimpleQueue: C-implemented, ~5x cheaper per op than queue.Queue on
    # the per-task hot path.
    task_queue: "queue.SimpleQueue" = queue.SimpleQueue()
    stop = threading.Event()

    # Direct listener: leased pushes, actor calls, borrow fetch/pin
    # (reference: the core worker's gRPC server, core_worker.h:278).
    from ray_tpu._private.config import CONFIG

    server = None
    if CONFIG.direct_transport:
        from ray_tpu._private.direct import DirectServer

        host_key = os.environ.get("RAY_TPU_HOST_KEY", "")
        session_dir = os.environ.get("RAY_TPU_SESSION_DIR")
        # Remote-node workers must be reachable cross-host; local workers
        # mirror the head's bind posture (loopback unless configured).
        tcp_bind = "0.0.0.0" if head_addr else CONFIG.tcp_host
        def on_exec(spec, c):
            if spec.func_blob is not None and spec.func_hash is not None:
                worker.register_func_blob(spec.func_hash, spec.func_blob)
            task_queue.put((spec, c))

        server = DirectServer(
            worker._owned, authkey, host_key,
            session_dir=session_dir,
            on_exec=on_exec,
            tcp_bind=tcp_bind)
        worker.enable_direct(server, host_key)

    def register():
        transport.send({"type": "register", "worker_id": worker_id.binary(),
                        "node_id": node_id.binary(), "pid": os.getpid(),
                        "direct_addr": server.address if server else None})

    def reconnect() -> bool:
        """Remote workers outlive a restarting head: retry the control
        connection within the reconnect window and re-register (the
        worker's actor/task state lives HERE, so surviving the outage is
        what preserves actors across head failover)."""
        if not head_addr:
            return False  # local workers die with the head process
        import time as _time

        from ray_tpu._private.config import CONFIG

        host, port = head_addr.rsplit(":", 1)
        deadline = _time.monotonic() + CONFIG.reconnect_window_s
        while _time.monotonic() < deadline:
            _time.sleep(1.0)
            try:
                newconn = Client((host, int(port)), family="AF_INET",
                                 authkey=authkey)
            except Exception:
                continue
            # Resends are held until registration completes on the new
            # conn, then every unacked in-flight request is resent (its
            # idempotency key makes the resend exactly-once at the head).
            transport.replace_conn(newconn, hold_resend=True)
            try:
                register()
            except Exception:
                continue  # head died again mid-handshake: keep retrying
            transport.release_resend()
            return True
        return False

    def reader():
        while True:
            try:
                msg = transport.conn.recv()
            except (EOFError, OSError):
                if not reconnect():
                    stop.set()
                    task_queue.put(None)
                    return
                continue
            t = msg.get("type")
            if t == "reply":
                transport.on_reply(msg)
            elif t == "execute":
                task_queue.put((msg["spec"], None))
            elif t == "shutdown":
                stop.set()
                task_queue.put(None)
                return

    threading.Thread(target=reader, name="rtpu-reader", daemon=True).start()
    register()

    # Tracing plane: direct-path tasks reply to their caller, bypassing
    # the head — a periodic flusher ships their spans on the node-stats
    # cadence so they still assemble (execute_task also flushes at task
    # start/end; this catches spans between tasks and long-running ones).
    from ray_tpu.util.tracing import tracing_enabled

    if tracing_enabled():
        from ray_tpu import observability as obs

        def span_flusher():
            import time as _time

            while not stop.is_set():
                _time.sleep(max(0.25, CONFIG.node_stats_period_s))
                try:
                    obs.flush(transport)
                except Exception:
                    pass

        threading.Thread(target=span_flusher, name="rtpu-span-flush",
                         daemon=True).start()

    def make_done(spec: TaskSpec):
        if server is not None and spec.task_id in server.cancelled:
            server.cancelled.discard(spec.task_id)
            from ray_tpu import exceptions as exc
            from ray_tpu._private import serialization as ser

            err = ser.pack(ser.serialize(exc.RayTpuError("task cancelled")))
            return {"t": "done", "task_id": spec.task_id.binary(),
                    "results": [], "error": err,
                    "error_str": "task cancelled"}
        from ray_tpu._private.worker import _DepsUnready

        # Bounce-on-pending applies only to leased NORMAL tasks; actor
        # calls must keep per-caller submission order, so they block
        # (their producers are never queued behind them on this channel).
        worker.ctx.direct_exec = True
        worker.ctx.bounce_ok = spec.task_type == TaskType.NORMAL
        try:
            msg = worker.execute_task(spec)
        except _DepsUnready:
            # A dependency is still pending at its owner: bounce the task
            # back to the submitter, who re-routes it through the head
            # (never block the lease queue — the producer may be queued
            # right behind us).
            return {"t": "done", "task_id": spec.task_id.binary(),
                    "unready": True, "results": [], "error": None,
                    "error_str": None}
        finally:
            worker.ctx.direct_exec = False
            worker.ctx.bounce_ok = False
        return {"t": "done", "task_id": msg["task_id"],
                "results": msg["results"], "error": msg["error"],
                "error_str": msg["error_str"]}

    # Batched completions from actor pool threads funnel through one reply
    # queue; the flusher groups whatever accumulated per caller connection
    # into a single frame (mirrors the exec batching on the submit side).
    # Main-loop tasks batch directly (no queue hop).
    reply_q: "queue.SimpleQueue" = queue.SimpleQueue()

    def reply_flusher():
        while True:
            item = reply_q.get()
            if item is None:
                return
            batch = [item]
            while len(batch) < 64:
                try:
                    nxt = reply_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    return
                batch.append(nxt)
            by_conn: dict = {}
            for c, done in batch:
                by_conn.setdefault(id(c), (c, []))[1].append(done)
            for _cid, (c, dones) in by_conn.items():
                server.send_on(c, dones[0] if len(dones) == 1
                               else {"t": "doneb", "dones": dones})

    if server is not None:
        threading.Thread(target=reply_flusher, name="rtpu-reply-flush",
                         daemon=True).start()

    # Lightweight actor pool (max_concurrency > 1): N threads over a
    # SimpleQueue — the ThreadPoolExecutor submit path costs more than a
    # short actor method.
    actor_q: "queue.SimpleQueue" = queue.SimpleQueue()
    pool_started = 0

    def _fallback_error(cause: BaseException):
        """Serialized stand-in error for a reply whose construction raised
        (e.g. a result-serialization double fault)."""
        from ray_tpu import exceptions as exc
        from ray_tpu._private import serialization as ser

        error_str = f"worker failed to build task reply: {cause!r}"
        try:
            err = ser.pack(ser.serialize(exc.RayTpuError(error_str)))
        except BaseException:
            err = None
        return err, error_str

    def _run_and_reply(spec: TaskSpec, reply_conn) -> None:
        """Execute + reply, with the invariant that the caller ALWAYS
        receives a completion message: a swallowed reply (a raise between
        task completion and the send, as a result-serialization double
        fault used to do) leaves the driver blocked on a future that can
        never resolve, which reads as a gang hang."""
        import time as _time

        if reply_conn is None:
            try:
                msg = worker.execute_task(spec)
            except BaseException as e:  # noqa: BLE001 — reply must flow
                err, error_str = _fallback_error(e)
                now = _time.time()
                msg = {"type": "task_done",
                       "task_id": spec.task_id.binary(),
                       "worker_id": worker.worker_id.binary(),
                       "spec": spec, "results": [], "error": err,
                       "error_str": error_str, "crashed": False,
                       "start": now, "end": now}
            # notify() (not raw send): in acked mode a dropped task_done
            # is retried instead of stranding the driver on its future.
            transport.notify(msg)
        else:
            try:
                done = make_done(spec)
            except BaseException as e:  # noqa: BLE001 — reply must flow
                err, error_str = _fallback_error(e)
                done = {"t": "done", "task_id": spec.task_id.binary(),
                        "results": [], "error": err,
                        "error_str": error_str}
            reply_q.put((reply_conn, done))

    def pool_worker():
        while True:
            item = actor_q.get()
            if item is None:
                return
            spec, reply_conn = item
            _run_and_reply(spec, reply_conn)

    def run_one(spec: TaskSpec, reply_conn=None):
        _run_and_reply(spec, reply_conn)

    done_buf: dict = {}

    def flush_done_buf():
        for _cid, (c, dones) in done_buf.items():
            server.send_on(c, dones[0] if len(dones) == 1
                           else {"t": "doneb", "dones": dones})
        done_buf.clear()

    while not stop.is_set():
        if done_buf:
            # Never block with unsent completions buffered (the next item
            # may take a branch that doesn't touch the buffer).
            try:
                item = task_queue.get_nowait()
            except queue.Empty:
                flush_done_buf()
                item = task_queue.get()
        else:
            item = task_queue.get()
        if item is None:
            break
        spec, reply_conn = item
        if spec.task_type == TaskType.ACTOR_CREATION and spec.max_concurrency > 1:
            for _ in range(spec.max_concurrency):
                threading.Thread(target=pool_worker, name="rtpu-actor",
                                 daemon=True).start()
            pool_started = spec.max_concurrency
        if pool_started and spec.task_type == TaskType.ACTOR_TASK:
            actor_q.put((spec, reply_conn))
        elif reply_conn is None:
            if done_buf:
                flush_done_buf()  # classic task may block for a long time
            run_one(spec, None)
        else:
            if done_buf and _has_ref_args(spec):
                # A task with ref args can BLOCK in arg resolution — and
                # a completion still sitting in this worker's done buffer
                # may be (transitively) the producer of one of those
                # refs.  Holding it while blocking deadlocks any
                # cross-actor dependency chain (the MPMD pipeline's 1F1B
                # ref wiring hits this on every step): flush first.
                flush_done_buf()
            try:
                done = make_done(spec)
            except BaseException as e:  # noqa: BLE001 — reply must flow
                err, error_str = _fallback_error(e)
                done = {"t": "done", "task_id": spec.task_id.binary(),
                        "results": [], "error": err,
                        "error_str": error_str}
            dones = done_buf.setdefault(id(reply_conn), (reply_conn, []))[1]
            dones.append(done)
            if len(dones) >= 32 or task_queue.empty():
                flush_done_buf()

    try:
        conn.close()
    except Exception:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
