"""Per-node resource-usage snapshots (reference: the dashboard reporter
agent collecting cpu/gpu/mem per node, python/ray/_private/metrics_agent.py
:375 + dashboard/modules/reporter/) — here a plain function the head's
monitor loop (local nodes) and each node agent (remote nodes) call on a
period, with results stored on the GCS node table and exported as
Prometheus gauges by the dashboard."""
from __future__ import annotations

import time
from typing import Optional


def host_snapshot() -> dict:
    """One host-level cpu/mem snapshot.  cpu_percent uses psutil's
    since-last-call accounting (first call returns 0.0), so call this
    ONCE per tick and share the result across co-hosted nodes —
    back-to-back calls measure a microsecond interval and return
    meaningless values."""
    import psutil

    vm = psutil.virtual_memory()
    return {
        "cpu_percent": float(psutil.cpu_percent(interval=None)),
        "mem_total_bytes": int(vm.total),
        "mem_used_bytes": int(vm.total - vm.available),
        "ts": time.time(),
    }


def collect_node_stats(store=None, num_workers: Optional[int] = None,
                       host_base: Optional[dict] = None) -> dict:
    """Per-node snapshot: host stats (taken fresh, or shared via
    `host_base` when several nodes live on one host) plus the node's own
    store usage and worker count."""
    stats = dict(host_base) if host_base is not None else host_snapshot()
    if num_workers is not None:
        stats["num_workers"] = int(num_workers)
    if store is not None:
        try:
            s = store.stats() or {}
            for k in ("capacity_bytes", "used_bytes", "num_objects",
                      "num_pinned"):
                if k in s:
                    stats[f"store_{k}"] = s[k]
        except Exception:
            pass
    return stats
