"""Node-to-node object transfer over TCP (the DCN object plane).

TPU-native equivalent of the reference's ObjectManager chunked push/pull
(src/ray/object_manager/object_manager.h:117, push_manager.h:29,
pull_manager.h:52).  Design differences, deliberately:

- **Pull-only, requester-driven** (the reference pulls for task args and
  pushes for ray.get): the process that needs the bytes connects to the
  store that has them and streams chunks into its own node store.  One
  mechanism, no push/pull coordination protocol.
- The wire is a `multiprocessing.connection` TCP channel (same framing +
  HMAC challenge as the control plane) instead of gRPC: the hot path is
  a handful of large objects (SampleBatches, checkpoints, dataset blocks),
  where per-message overhead is irrelevant and `send_bytes` is a single
  syscall per chunk.
- Chunk size 4 MiB (reference default 1 MiB, ray_config_def.h) — fewer
  framing round-trips on DCN-class links.

The server runs a thread inside whichever process owns a node store (the
head process for in-process raylets, the node agent for remote nodes) and
reads under a pin so eviction can never recycle a slot mid-stream.
"""
from __future__ import annotations

import queue
import socket
import threading
import time
import traceback
from multiprocessing.connection import Client, Listener
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

CHUNK = 4 * 1024 * 1024

_routable_ip_cache: Optional[str] = None
_routable_ip_lock = threading.Lock()


def routable_ip() -> str:
    """Best-effort externally-routable IP of this host.

    Cached after the first call: the probe opens a UDP socket and does two
    syscalls, and callers hit this once per transfer connection — a host's
    routable address does not change within a process's lifetime."""
    global _routable_ip_cache
    ip = _routable_ip_cache
    if ip is not None:
        return ip
    with _routable_ip_lock:
        if _routable_ip_cache is None:
            _routable_ip_cache = _probe_routable_ip()
        return _routable_ip_cache


def _probe_routable_ip() -> str:
    try:
        u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        u.connect(("8.8.8.8", 80))
        ip = u.getsockname()[0]
        u.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def _chunk_size() -> int:
    try:
        from ray_tpu._private.config import CONFIG

        return int(CONFIG.transfer_chunk_bytes) or CHUNK
    except Exception:
        return CHUNK


def _pipeline_depth() -> int:
    try:
        from ray_tpu._private.config import CONFIG

        return max(0, int(CONFIG.transfer_pipeline_depth))
    except Exception:
        return 2


def wire_store_reporting(store, send) -> None:
    """Wire a remote-process store's evict/spill callbacks to the head.

    The head's directory must learn about evictions and spills in agent and
    driver processes, or it hands out resolutions for bytes that no longer
    exist (local stores report through in-process callbacks instead —
    head.py add_node)."""

    def on_evict(oid: ObjectID):
        try:
            send({"type": "object_evicted", "oid": oid.binary()})
        except Exception:
            pass

    def on_spill(oid: ObjectID):
        rec = store.spilled_lookup(oid)
        if rec is None:
            return
        try:
            send({"type": "object_spilled", "oid": oid.binary(),
                  "path": rec["path"], "meta": rec["meta"],
                  "size": rec["size"]})
        except Exception:
            pass

    store.evict_callback = on_evict
    store.spill_callback = on_spill


class ObjectTransferServer:
    """Serves chunked object reads from one node store.

    Protocol (per connection, may serve many requests):
      recv {"oid": bytes}
      send {"ok": True, "meta": bytes, "size": int} then ceil(size/CHUNK)
           raw byte chunks via send_bytes
      or   {"ok": False, "error": str}
    """

    def __init__(self, store, authkey: bytes, host: str = "0.0.0.0"):
        self.store = store
        self._listener = Listener((host, 0), family="AF_INET",
                                  authkey=authkey)
        self.port = self._listener.address[1]
        self.address: Tuple[str, int] = (routable_ip(), self.port)
        self._shutdown = False
        # Transfer-plane traffic actually served by this store (locality
        # smokes assert "quiet plane" on these, not just on directory
        # accounting).  Plain ints under the GIL — per-object bumps.
        self.served_objects = 0
        self.served_bytes = 0
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="rtpu-xfer-accept", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rtpu-xfer", daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while True:
                req = conn.recv()
                self._serve_one(conn, ObjectID(req["oid"]),
                                req.get("tc"))
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _serve_one(self, conn, oid: ObjectID, tc=None):
        t0 = time.time()
        served0 = self.served_bytes
        # Pin while streaming: eviction must not recycle the buffer under us
        # (plasma's client in-use-count contract).
        self.store.pin(oid)
        try:
            got = self._read(oid)
            if got is None:
                conn.send({"ok": False,
                           "error": f"object {oid} not in this store"})
                return
            meta, size, chunks = got
            self.served_objects += 1
            self.served_bytes += size
            conn.send({"ok": True, "meta": bytes(meta), "size": size})
            chunk = _chunk_size()
            depth = _pipeline_depth()
            if size == 0:
                conn.send_bytes(b"")
                return
            if depth >= 2 and size > chunk:
                # Pipelined: a producer thread reads/slices chunk N+1..N+d
                # while this thread's send_bytes(chunk N) blocks on the
                # socket, so disk reads (spilled objects) and socket
                # writes overlap instead of strictly alternating.
                self._send_pipelined(conn, chunks, depth)
            else:
                for piece in chunks:
                    conn.send_bytes(piece)
        finally:
            self.store.unpin(oid)
            if tc is not None:
                # Serve-side span inside the puller's trace — the
                # cross-process flow edge for transfer-plane bytes.
                try:
                    from ray_tpu import observability as obs

                    obs.record("transfer.pull", t0, time.time(),
                               ctx=tuple(tc), oid=oid.hex(),
                               bytes=self.served_bytes - served0)
                except Exception:
                    pass

    @staticmethod
    def _send_pipelined(conn, chunks, depth: int):
        q: "queue.Queue" = queue.Queue(maxsize=max(1, depth - 1))
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for piece in chunks:
                    if not put(piece):
                        return  # consumer bailed (socket error): closing
                        # the generator runs its finally (file close)
                put(_END)
            except BaseException as e:  # noqa: BLE001 — forwarded to sender
                put(e)

        t = threading.Thread(target=produce, name="rtpu-xfer-read",
                             daemon=True)
        t.start()
        try:
            while True:
                piece = q.get()
                if piece is _END:
                    return
                if isinstance(piece, BaseException):
                    raise piece
                conn.send_bytes(piece)
        finally:
            stop.set()
            t.join(timeout=5.0)

    def _read(self, oid: ObjectID):
        """Resolve an object to (meta, size, chunk_iterable); None if the
        store has no trace of it."""
        chunk = _chunk_size()
        got = self.store.get(oid)
        if got is not None:
            meta, data = got
            return meta, len(data), _view_chunks(data, chunk)
        # Arena-resident object (owner-process put): copy out under the
        # store lock — an arena slot can be recycled by a concurrent
        # delete, and unlike shm segments the mapping gives no lifetime
        # guarantee to readers in this process.
        lock = getattr(self.store, "_lock", None)
        if lock is None:
            return None
        with lock:
            hit = self.store.arena_lookup(oid)
            if hit is not None:
                from ray_tpu._native import ArenaReader

                view = ArenaReader.view(hit["store"], hit["offset"],
                                        hit["size"], hit["capacity"])
                data = memoryview(bytes(view))
                return hit["meta"], len(data), _view_chunks(data, chunk)
        # Spilled-to-disk fallback: stream straight off the spill file
        # (reference: spilled_object_reader.h) — chunked reads feed the
        # pipelined sender, so the whole object is never buffered here.
        lookup = getattr(self.store, "spilled_lookup", None)
        rec = lookup(oid) if lookup is not None else None
        if rec is not None:
            try:
                f = open(rec["path"], "rb")
            except OSError:
                return None
            return rec["meta"], rec["size"], _file_chunks(f, chunk)
        return None

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:
            pass


def _view_chunks(data: memoryview, chunk: int):
    for off in range(0, len(data), chunk):
        yield data[off:off + chunk]


def _file_chunks(f, chunk: int):
    try:
        while True:
            piece = f.read(chunk)
            if not piece:
                return
            yield piece
    finally:
        f.close()


def _client_with_deadline(addr: Tuple[str, int], authkey: bytes,
                          timeout: float):
    """Client() with a bounded connect+handshake.

    A SIGSTOPped/hung peer ACCEPTS the TCP connection (kernel backlog)
    and then never answers the HMAC challenge — a plain Client() blocks
    forever inside answer_challenge, before any per-chunk deadline can
    apply.  The handshake runs on a helper thread; past the deadline the
    attempt is abandoned (the thread closes the socket if it ever
    completes) and the caller's retry/failover takes over."""
    if not timeout or timeout <= 0:
        return Client(tuple(addr), family="AF_INET", authkey=authkey)
    box: dict = {}
    lock = threading.Lock()
    done = threading.Event()

    def run():
        try:
            c = Client(tuple(addr), family="AF_INET", authkey=authkey)
        except BaseException as e:  # noqa: BLE001 — forwarded to caller
            with lock:
                box["err"] = e
            done.set()
            return
        with lock:
            if box.get("abandoned"):
                abandoned = True
            else:
                box["conn"] = c
                abandoned = False
        done.set()
        if abandoned:
            try:
                c.close()
            except Exception:
                pass

    threading.Thread(target=run, name="rtpu-xfer-conn", daemon=True).start()
    if not done.wait(timeout):
        with lock:
            conn = box.get("conn")
            if conn is None:
                box["abandoned"] = True
        if box.get("abandoned"):
            raise OSError(
                f"transfer connect to {addr} stalled past {timeout}s")
        return conn
    if "err" in box:
        raise box["err"]
    return box["conn"]


class TransferClient:
    """Pulls objects from remote transfer servers; caches connections."""

    def __init__(self, authkey: bytes):
        self.authkey = authkey
        self._conns = {}
        self._conn_locks = {}  # addr -> per-connection stream lock
        self._lock = threading.Lock()  # guards the two maps only

    def _conn_for(self, addr: Tuple[str, int]):
        addr = tuple(addr)
        with self._lock:
            conn = self._conns.get(addr)
            lock = self._conn_locks.setdefault(addr, threading.Lock())
        if conn is not None:
            return conn, lock
        from ray_tpu._private.config import CONFIG

        conn = _client_with_deadline(addr, self.authkey,
                                     float(CONFIG.transfer_timeout_s))
        with self._lock:
            old = self._conns.setdefault(addr, conn)
        if old is not conn:
            conn.close()
            return old, lock
        return conn, lock

    @staticmethod
    def _await_bytes(conn, timeout_s: float, oid: ObjectID, what: str):
        """Per-chunk progress deadline: a stream that stops moving raises
        instead of blocking recv() forever (a severed peer whose FIN was
        lost looks exactly like a slow one — bound it)."""
        if timeout_s and timeout_s > 0 and not conn.poll(timeout_s):
            raise OSError(
                f"transfer of {oid} stalled: no {what} for {timeout_s}s")

    def _invalidate(self, addr):
        with self._lock:
            conn = self._conns.pop(tuple(addr), None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def pull(self, addr: Tuple[str, int], oid: ObjectID,
             sink=None) -> Tuple[bytes, bytes]:
        """Fetch (meta, data) for oid from the store at addr.

        If `sink` (a writable buffer of the right size, e.g. a local shm
        view) is provided, chunks are written into it and `data` returns
        that buffer's bytes are NOT copied again — the caller owns sink.
        Connection errors/stalls invalidate the cached conn and retry
        with backoff (`transfer_retries`); each chunk must arrive within
        `transfer_timeout_s` or the attempt counts as failed."""
        from ray_tpu._private.chaos import net_fault
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.retry import RetryPolicy

        retries = max(0, int(CONFIG.transfer_retries))
        timeout_s = float(CONFIG.transfer_timeout_s)
        policy = RetryPolicy(base=0.05, cap=1.0)
        tc = None
        try:
            from ray_tpu import observability as obs
            from ray_tpu.util.tracing import tracing_enabled

            if tracing_enabled():
                tc = obs.get_context()
        except Exception:
            pass
        for attempt in range(retries + 1):
            act = net_fault("pull")
            if act is not None:
                kind, delay_ms = act
                if kind == "delay":
                    time.sleep(delay_ms / 1000.0)
                elif kind in ("drop", "sever"):
                    # The data channel is strict request/response: a lost
                    # frame is indistinguishable from a severed conn, so
                    # both surface as a connection failure (and retry).
                    self._invalidate(addr)
                    if attempt >= retries:
                        raise OSError("chaos: transfer connection severed")
                    time.sleep(policy.delay(attempt + 1))
                    continue
            conn, conn_lock = self._conn_for(addr)
            try:
                # One in-flight request per CONNECTION (request/response
                # protocol); pulls against different servers overlap.
                with conn_lock:
                    req = {"oid": oid.binary()}
                    if tc is not None:
                        req["tc"] = tc
                    conn.send(req)
                    self._await_bytes(conn, timeout_s, oid, "header")
                    hdr = conn.recv()
                    if not hdr["ok"]:
                        raise KeyError(hdr["error"])
                    size = hdr["size"]
                    if sink is not None:
                        view = memoryview(sink)
                        off = 0
                        if size == 0:
                            self._await_bytes(conn, timeout_s, oid, "chunk")
                            conn.recv_bytes()
                        while off < size:
                            self._await_bytes(conn, timeout_s, oid, "chunk")
                            n = conn.recv_bytes_into(view[off:])
                            off += n
                        return hdr["meta"], None
                    parts = []
                    got = 0
                    while got < size:
                        self._await_bytes(conn, timeout_s, oid, "chunk")
                        b = conn.recv_bytes()
                        parts.append(b)
                        got += len(b)
                    if size == 0:
                        self._await_bytes(conn, timeout_s, oid, "chunk")
                        conn.recv_bytes()
                    return hdr["meta"], b"".join(parts)
            except (EOFError, OSError, BrokenPipeError):
                self._invalidate(addr)
                if attempt >= retries:
                    raise
                time.sleep(policy.delay(attempt + 1))
        raise RuntimeError("unreachable")

    def close(self):
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except Exception:
                    pass
            self._conns.clear()
