"""Node-to-node object transfer over TCP (the DCN object plane).

TPU-native equivalent of the reference's ObjectManager chunked push/pull
(src/ray/object_manager/object_manager.h:117, push_manager.h:29,
pull_manager.h:52).  Design differences, deliberately:

- **Pull-only, requester-driven** (the reference pulls for task args and
  pushes for ray.get): the process that needs the bytes connects to the
  store that has them and streams chunks into its own node store.  One
  mechanism, no push/pull coordination protocol.
- The wire is a `multiprocessing.connection` TCP channel (same framing +
  HMAC challenge as the control plane) instead of gRPC: the hot path is
  a handful of large objects (SampleBatches, checkpoints, dataset blocks),
  where per-message overhead is irrelevant and `send_bytes` is a single
  syscall per chunk.
- Chunk size 4 MiB (reference default 1 MiB, ray_config_def.h) — fewer
  framing round-trips on DCN-class links.

The server runs a thread inside whichever process owns a node store (the
head process for in-process raylets, the node agent for remote nodes) and
reads under a pin so eviction can never recycle a slot mid-stream.
"""
from __future__ import annotations

import socket
import threading
import traceback
from multiprocessing.connection import Client, Listener
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

CHUNK = 4 * 1024 * 1024


def routable_ip() -> str:
    """Best-effort externally-routable IP of this host."""
    try:
        u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        u.connect(("8.8.8.8", 80))
        ip = u.getsockname()[0]
        u.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def wire_store_reporting(store, send) -> None:
    """Wire a remote-process store's evict/spill callbacks to the head.

    The head's directory must learn about evictions and spills in agent and
    driver processes, or it hands out resolutions for bytes that no longer
    exist (local stores report through in-process callbacks instead —
    head.py add_node)."""

    def on_evict(oid: ObjectID):
        try:
            send({"type": "object_evicted", "oid": oid.binary()})
        except Exception:
            pass

    def on_spill(oid: ObjectID):
        rec = store.spilled_lookup(oid)
        if rec is None:
            return
        try:
            send({"type": "object_spilled", "oid": oid.binary(),
                  "path": rec["path"], "meta": rec["meta"],
                  "size": rec["size"]})
        except Exception:
            pass

    store.evict_callback = on_evict
    store.spill_callback = on_spill


class ObjectTransferServer:
    """Serves chunked object reads from one node store.

    Protocol (per connection, may serve many requests):
      recv {"oid": bytes}
      send {"ok": True, "meta": bytes, "size": int} then ceil(size/CHUNK)
           raw byte chunks via send_bytes
      or   {"ok": False, "error": str}
    """

    def __init__(self, store, authkey: bytes, host: str = "0.0.0.0"):
        self.store = store
        self._listener = Listener((host, 0), family="AF_INET",
                                  authkey=authkey)
        self.port = self._listener.address[1]
        self.address: Tuple[str, int] = (routable_ip(), self.port)
        self._shutdown = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="rtpu-xfer-accept", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rtpu-xfer", daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while True:
                req = conn.recv()
                self._serve_one(conn, ObjectID(req["oid"]))
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _serve_one(self, conn, oid: ObjectID):
        # Pin while streaming: eviction must not recycle the buffer under us
        # (plasma's client in-use-count contract).
        self.store.pin(oid)
        try:
            got = self._read(oid)
            if got is None:
                conn.send({"ok": False,
                           "error": f"object {oid} not in this store"})
                return
            meta, data = got
            size = len(data)
            conn.send({"ok": True, "meta": bytes(meta), "size": size})
            for off in range(0, size, CHUNK):
                conn.send_bytes(data[off:off + CHUNK])
            if size == 0:
                conn.send_bytes(b"")
        finally:
            self.store.unpin(oid)

    def _read(self, oid: ObjectID) -> Optional[Tuple[bytes, memoryview]]:
        got = self.store.get(oid)
        if got is not None:
            return got
        # Arena-resident object (owner-process put): copy out under the
        # store lock — an arena slot can be recycled by a concurrent
        # delete, and unlike shm segments the mapping gives no lifetime
        # guarantee to readers in this process.
        lock = getattr(self.store, "_lock", None)
        if lock is None:
            return None
        with lock:
            hit = self.store.arena_lookup(oid)
            if hit is not None:
                from ray_tpu._native import ArenaReader

                view = ArenaReader.view(hit["store"], hit["offset"],
                                        hit["size"], hit["capacity"])
                return hit["meta"], memoryview(bytes(view))
        # Spilled-to-disk fallback: serve the bytes from the spill file
        # (reference: spilled_object_reader.h).
        spilled = getattr(self.store, "read_spilled", None)
        if spilled is not None:
            got = spilled(oid)
            if got is not None:
                meta, data = got
                return meta, memoryview(data)
        return None

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:
            pass


class TransferClient:
    """Pulls objects from remote transfer servers; caches connections."""

    def __init__(self, authkey: bytes):
        self.authkey = authkey
        self._conns = {}
        self._conn_locks = {}  # addr -> per-connection stream lock
        self._lock = threading.Lock()  # guards the two maps only

    def _conn_for(self, addr: Tuple[str, int]):
        addr = tuple(addr)
        with self._lock:
            conn = self._conns.get(addr)
            lock = self._conn_locks.setdefault(addr, threading.Lock())
        if conn is not None:
            return conn, lock
        conn = Client(tuple(addr), family="AF_INET", authkey=self.authkey)
        with self._lock:
            old = self._conns.setdefault(addr, conn)
        if old is not conn:
            conn.close()
            return old, lock
        return conn, lock

    def pull(self, addr: Tuple[str, int], oid: ObjectID,
             sink=None) -> Tuple[bytes, bytes]:
        """Fetch (meta, data) for oid from the store at addr.

        If `sink` (a writable buffer of the right size, e.g. a local shm
        view) is provided, chunks are written into it and `data` returns
        that buffer's bytes are NOT copied again — the caller owns sink.
        Connection errors invalidate the cached conn and retry once."""
        for attempt in (0, 1):
            conn, conn_lock = self._conn_for(addr)
            try:
                # One in-flight request per CONNECTION (request/response
                # protocol); pulls against different servers overlap.
                with conn_lock:
                    conn.send({"oid": oid.binary()})
                    hdr = conn.recv()
                    if not hdr["ok"]:
                        raise KeyError(hdr["error"])
                    size = hdr["size"]
                    if sink is not None:
                        view = memoryview(sink)
                        off = 0
                        if size == 0:
                            conn.recv_bytes()
                        while off < size:
                            n = conn.recv_bytes_into(view[off:])
                            off += n
                        return hdr["meta"], None
                    parts = []
                    got = 0
                    while got < size:
                        b = conn.recv_bytes()
                        parts.append(b)
                        got += len(b)
                    if size == 0:
                        conn.recv_bytes()
                    return hdr["meta"], b"".join(parts)
            except (EOFError, OSError, BrokenPipeError):
                with self._lock:
                    self._conns.pop(tuple(addr), None)
                if attempt:
                    raise
        raise RuntimeError("unreachable")

    def close(self):
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except Exception:
                    pass
            self._conns.clear()
