"""Node-to-node object transfer over TCP (the DCN object plane).

TPU-native equivalent of the reference's ObjectManager chunked push/pull
(src/ray/object_manager/object_manager.h:117, push_manager.h:29,
pull_manager.h:52).  Design differences, deliberately:

- **Pull-only, requester-driven** (the reference pulls for task args and
  pushes for ray.get): the process that needs the bytes connects to the
  store that has them and streams chunks into its own node store.  One
  mechanism, no push/pull coordination protocol.
- The wire is a `multiprocessing.connection` TCP channel (same framing +
  HMAC challenge as the control plane) instead of gRPC: the hot path is
  a handful of large objects (SampleBatches, checkpoints, dataset blocks),
  where per-message overhead is irrelevant and `send_bytes` is a single
  syscall per chunk.
- Chunk size 4 MiB (reference default 1 MiB, ray_config_def.h) — fewer
  framing round-trips on DCN-class links.

The server runs a thread inside whichever process owns a node store (the
head process for in-process raylets, the node agent for remote nodes) and
reads under a pin so eviction can never recycle a slot mid-stream.
"""
from __future__ import annotations

import queue
import random
import socket
import threading
import time
import traceback
from collections import OrderedDict
from multiprocessing.connection import Client, Listener
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.ids import ObjectID

CHUNK = 4 * 1024 * 1024


class RangeUnavailableError(KeyError):
    """The peer exists and holds the object partially, but not the
    requested chunk range (it evicted the record, or the directory's
    bitmap was stale).  Distinct from KeyError("not in this store") so
    the striped scheduler can drop the SOURCE without burning a pull
    retry ladder on it."""


# ---------------------------------------------------------------------------
# transfer_* metrics: process-local counters (always available, asserted by
# smokes/benches via transfer_stats()) mirrored into util.metrics so
# prometheus_text() exports them.  KV flushes are best-effort — transfer
# happens in worker/agent processes whose kv plane may be mid-teardown.
# ---------------------------------------------------------------------------
_stats_lock = threading.Lock()
_STATS: Dict[str, float] = {
    "striped_pulls": 0,         # pulls that went through pull_striped
    "striped_bytes": 0,         # bytes landed by striped ranges
    "ranges_completed": 0,      # chunk ranges fetched (any source)
    "ranges_from_partial": 0,   # ranges served by a partial (non-owner) peer
    "range_reassignments": 0,   # ranges requeued off a dead/slow source
    "range_retries": 0,         # per-range wire retries (chaos/drops)
    "active_streams": 0,        # currently-open range/pull streams
    "served_ranges": 0,         # server side: range requests served
    "served_partial_ranges": 0,  # ... of those, out of a partial record
    "served_partial_bytes": 0,
    "coalesced_pulls": 0,       # same-oid pulls that waited on the leader
}
_meters: Dict[str, object] = {}


def _stat_add(name: str, delta: float = 1.0) -> None:
    with _stats_lock:
        _STATS[name] = _STATS.get(name, 0.0) + delta
    if name == "active_streams":
        _gauge_streams()
        return
    try:
        m = _meters.get(name)
        if m is None:
            from ray_tpu.util.metrics import Meter

            m = _meters[name] = Meter(f"transfer_{name}_total")
        m.mark(delta)
    except Exception:
        pass


def _gauge_streams() -> None:
    try:
        g = _meters.get("_streams_gauge")
        if g is None:
            from ray_tpu.util.metrics import Gauge

            g = _meters["_streams_gauge"] = Gauge(
                "transfer_active_streams",
                "Open transfer-plane streams in this process.")
        g.set(_STATS["active_streams"])
    except Exception:
        pass


def _peer_meter(peer: str):
    key = f"_peer:{peer}"
    m = _meters.get(key)
    if m is None:
        from ray_tpu.util.metrics import Meter

        m = Meter("transfer_peer_bytes_total",
                  "Bytes pulled over the transfer plane, per source peer.",
                  tag_keys=("peer",)).set_default_tags({"peer": peer})
        _meters[key] = m
    return m


def transfer_stats() -> Dict[str, float]:
    """Snapshot of this process's transfer-plane counters (the smoke /
    bench proof surface; mirrors the transfer_* prometheus metrics)."""
    with _stats_lock:
        return dict(_STATS)

_routable_ip_cache: Optional[str] = None
_routable_ip_lock = threading.Lock()


def routable_ip() -> str:
    """Best-effort externally-routable IP of this host.

    Cached after the first call: the probe opens a UDP socket and does two
    syscalls, and callers hit this once per transfer connection — a host's
    routable address does not change within a process's lifetime."""
    global _routable_ip_cache
    ip = _routable_ip_cache
    if ip is not None:
        return ip
    with _routable_ip_lock:
        if _routable_ip_cache is None:
            _routable_ip_cache = _probe_routable_ip()
        return _routable_ip_cache


def _probe_routable_ip() -> str:
    try:
        u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        u.connect(("8.8.8.8", 80))
        ip = u.getsockname()[0]
        u.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def _chunk_size() -> int:
    try:
        from ray_tpu._private.config import CONFIG

        return int(CONFIG.transfer_chunk_bytes) or CHUNK
    except Exception:
        return CHUNK


def _pipeline_depth() -> int:
    try:
        from ray_tpu._private.config import CONFIG

        return max(0, int(CONFIG.transfer_pipeline_depth))
    except Exception:
        return 2


def wire_store_reporting(store, send) -> None:
    """Wire a remote-process store's evict/spill callbacks to the head.

    The head's directory must learn about evictions and spills in agent and
    driver processes, or it hands out resolutions for bytes that no longer
    exist (local stores report through in-process callbacks instead —
    head.py add_node)."""

    def on_evict(oid: ObjectID):
        try:
            send({"type": "object_evicted", "oid": oid.binary()})
        except Exception:
            pass

    def on_spill(oid: ObjectID):
        rec = store.spilled_lookup(oid)
        if rec is None:
            return
        try:
            send({"type": "object_spilled", "oid": oid.binary(),
                  "path": rec["path"], "meta": rec["meta"],
                  "size": rec["size"]})
        except Exception:
            pass

    store.evict_callback = on_evict
    store.spill_callback = on_spill


class _PartialRecord:
    """An in-progress (or just-completed) pull this process can re-serve.

    ``buf`` is a writable view over the destination segment the owner is
    still landing ranges into; ``have`` is the set of chunk indices whose
    bytes are final.  The registry serves a range iff every chunk in it
    landed — readers never observe torn bytes because a chunk is marked
    only after its recv_bytes_into completed."""

    __slots__ = ("buf", "size", "chunk", "have", "nchunks", "meta",
                 "complete")

    def __init__(self, buf, size: int, chunk: int):
        self.buf = buf
        self.size = size
        self.chunk = max(1, chunk)
        self.have: Set[int] = set()
        self.nchunks = (size + self.chunk - 1) // self.chunk
        self.meta: Optional[bytes] = None
        self.complete = False

    def covers(self, off: int, length: int) -> bool:
        if self.complete:
            return True
        lo = off // self.chunk
        hi = (off + length + self.chunk - 1) // self.chunk
        return all(i in self.have for i in range(lo, hi))


class ObjectTransferServer:
    """Serves chunked object reads from one node store and/or this
    process's partial-pull registry (cooperative broadcast).

    Protocol (per connection, may serve many requests):
      recv {"oid": bytes[, "off": int, "len": int]}
      send {"ok": True, "meta": bytes|None, "size": total_size} then the
           requested byte range (whole object when off/len absent) as raw
           chunks via send_bytes
      or   {"ok": False, "error": str[, "code": "norange"]}

    ``code: norange`` means "I hold this object partially but not that
    range" — the puller drops this source without failing the pull.

    ``store=None`` runs a store-less peer server: it serves ONLY the
    partial registry.  Worker processes use that mode to re-serve ranges
    of objects they are themselves still pulling, which is what turns a
    one-to-N broadcast into a dissemination mesh instead of N unicast
    streams through the owner.
    """

    # Completed partial records kept around for late pullers; in-progress
    # records are never evicted (their owner drops them on failure).
    PARTIAL_CAP = 32

    def __init__(self, store, authkey: bytes, host: str = "0.0.0.0"):
        self.store = store
        self._listener = Listener((host, 0), family="AF_INET",
                                  authkey=authkey)
        self.port = self._listener.address[1]
        self.address: Tuple[str, int] = (routable_ip(), self.port)
        self._shutdown = False
        # Transfer-plane traffic actually served by this store (locality
        # smokes assert "quiet plane" on these, not just on directory
        # accounting).  Plain ints under the GIL — per-object bumps.
        self.served_objects = 0
        self.served_bytes = 0
        self.served_ranges = 0
        self.served_partial_ranges = 0
        self.served_partial_bytes = 0
        self._partials: "OrderedDict[ObjectID, _PartialRecord]" = \
            OrderedDict()
        self._plock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="rtpu-xfer-accept", daemon=True)
        self._thread.start()

    # ---- partial registry (cooperative broadcast) ----
    def register_partial(self, oid: ObjectID, buf, size: int,
                         chunk: int) -> None:
        with self._plock:
            self._partials[oid] = _PartialRecord(buf, size, chunk)
            self._partials.move_to_end(oid)
            while len(self._partials) > self.PARTIAL_CAP:
                victim = next((k for k, r in self._partials.items()
                               if r.complete), None)
                if victim is None:
                    break  # all in-progress: owners drop them themselves
                self._partials.pop(victim)

    def mark_range(self, oid: ObjectID, off: int, length: int) -> List[int]:
        """Record [off, off+length) as landed; returns the newly-complete
        chunk indices (what the owner should advertise)."""
        with self._plock:
            rec = self._partials.get(oid)
            if rec is None:
                return []
            # Only chunks FULLY inside [off, off+length) become servable
            # (ceil the left edge, floor the right — the final partial
            # chunk counts once the range reaches the object's end).
            lo = (off + rec.chunk - 1) // rec.chunk
            hi = (rec.nchunks if off + length >= rec.size
                  else (off + length) // rec.chunk)
            fresh = [i for i in range(lo, min(hi, rec.nchunks))
                     if i not in rec.have]
            rec.have.update(fresh)
            return fresh

    def complete_partial(self, oid: ObjectID, meta: bytes) -> None:
        with self._plock:
            rec = self._partials.get(oid)
            if rec is not None:
                rec.meta = meta
                rec.complete = True
                rec.have = set(range(rec.nchunks))

    def drop_partial(self, oid: ObjectID) -> bool:
        with self._plock:
            return self._partials.pop(oid, None) is not None

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rtpu-xfer", daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while True:
                req = conn.recv()
                self._serve_one(conn, ObjectID(req["oid"]),
                                req.get("off"), req.get("len"),
                                req.get("tc"))
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _serve_partial(self, conn, oid: ObjectID, off, length) -> bool:
        """Serve a range out of the partial registry.  Returns True when
        the request was answered (hit, or a norange refusal for a record
        we own but whose range hasn't landed)."""
        with self._plock:
            rec = self._partials.get(oid)
            if rec is None:
                return False
            if off is None:
                off, length = 0, rec.size
                if not rec.complete:
                    # A whole-object request needs meta; only a sealed
                    # record can answer it.
                    conn.send({"ok": False, "code": "norange",
                               "error": f"object {oid} incomplete here"})
                    return True
            length = max(0, min(length, rec.size - off))
            if not rec.covers(off, length):
                conn.send({"ok": False, "code": "norange",
                           "error": f"range {off}+{length} of {oid} "
                                    "not landed here yet"})
                return True
            meta = rec.meta
            view = memoryview(rec.buf)[off:off + length]
        try:
            self.served_ranges += 1
            self.served_partial_ranges += 1
            self.served_partial_bytes += length
            self.served_bytes += length
            _stat_add("served_ranges")
            _stat_add("served_partial_ranges")
            _stat_add("served_partial_bytes", length)
            conn.send({"ok": True,
                       "meta": bytes(meta) if meta is not None else None,
                       "size": rec.size})
            if length == 0:
                conn.send_bytes(b"")
                return True
            chunk = _chunk_size()
            for poff in range(0, length, chunk):
                conn.send_bytes(view[poff:poff + chunk])
            return True
        finally:
            view.release()

    def _serve_one(self, conn, oid: ObjectID, off=None, length=None,
                   tc=None):
        t0 = time.time()
        served0 = self.served_bytes
        try:
            # Cooperative path first: a range this process is still
            # landing (or just sealed) is served straight out of the
            # destination buffer, store or no store.
            if self._serve_partial(conn, oid, off, length):
                return
            if self.store is None:
                conn.send({"ok": False,
                           "error": f"object {oid} not at this peer"})
                return
            # Pin while streaming: eviction must not recycle the buffer
            # under us (plasma's client in-use-count contract).
            self.store.pin(oid)
            try:
                got = self._read(oid, off, length)
                if got is None:
                    conn.send({"ok": False,
                               "error": f"object {oid} not in this store"})
                    return
                meta, size, span, chunks = got
                self.served_objects += 1
                self.served_bytes += span
                if off is not None:
                    self.served_ranges += 1
                    _stat_add("served_ranges")
                conn.send({"ok": True, "meta": bytes(meta), "size": size})
                chunk = _chunk_size()
                depth = _pipeline_depth()
                if span == 0:
                    conn.send_bytes(b"")
                    return
                if depth >= 2 and span > chunk:
                    # Pipelined: a producer thread reads/slices chunk
                    # N+1..N+d while this thread's send_bytes(chunk N)
                    # blocks on the socket, so disk reads (spilled
                    # objects) and socket writes overlap instead of
                    # strictly alternating.
                    self._send_pipelined(conn, chunks, depth)
                else:
                    for piece in chunks:
                        conn.send_bytes(piece)
            finally:
                self.store.unpin(oid)
        finally:
            if tc is not None:
                # Serve-side span inside the puller's trace — the
                # cross-process flow edge for transfer-plane bytes.
                try:
                    from ray_tpu import observability as obs

                    obs.record("transfer.pull", t0, time.time(),
                               ctx=tuple(tc), oid=oid.hex(),
                               bytes=self.served_bytes - served0,
                               range=off is not None)
                except Exception:
                    pass

    @staticmethod
    def _send_pipelined(conn, chunks, depth: int):
        q: "queue.Queue" = queue.Queue(maxsize=max(1, depth - 1))
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for piece in chunks:
                    if not put(piece):
                        return  # consumer bailed (socket error): closing
                        # the generator runs its finally (file close)
                put(_END)
            except BaseException as e:  # noqa: BLE001 — forwarded to sender
                put(e)

        t = threading.Thread(target=produce, name="rtpu-xfer-read",
                             daemon=True)
        t.start()
        try:
            while True:
                piece = q.get()
                if piece is _END:
                    return
                if isinstance(piece, BaseException):
                    raise piece
                conn.send_bytes(piece)
        finally:
            stop.set()
            t.join(timeout=5.0)

    @staticmethod
    def _clamp(size: int, off, length) -> Tuple[int, int]:
        if off is None:
            return 0, size
        off = max(0, min(int(off), size))
        return off, max(0, min(int(length), size - off))

    def _read(self, oid: ObjectID, off=None, length=None):
        """Resolve an object (or a byte range of it) to
        (meta, total_size, span_bytes, chunk_iterable); None if the store
        has no trace of it."""
        chunk = _chunk_size()
        got = self.store.get(oid)
        if got is not None:
            meta, data = got
            o, ln = self._clamp(len(data), off, length)
            return (meta, len(data), ln,
                    _view_chunks(memoryview(data)[o:o + ln], chunk))
        # Arena-resident object (owner-process put): copy out under the
        # store lock — an arena slot can be recycled by a concurrent
        # delete, and unlike shm segments the mapping gives no lifetime
        # guarantee to readers in this process.
        lock = getattr(self.store, "_lock", None)
        if lock is None:
            return None
        with lock:
            hit = self.store.arena_lookup(oid)
            if hit is not None:
                from ray_tpu._native import ArenaReader

                view = ArenaReader.view(hit["store"], hit["offset"],
                                        hit["size"], hit["capacity"])
                data = memoryview(bytes(view))
                o, ln = self._clamp(len(data), off, length)
                return (hit["meta"], len(data), ln,
                        _view_chunks(data[o:o + ln], chunk))
        # Spilled-to-disk fallback: stream straight off the spill file
        # (reference: spilled_object_reader.h) — chunked reads feed the
        # pipelined sender, so the whole object is never buffered here.
        lookup = getattr(self.store, "spilled_lookup", None)
        rec = lookup(oid) if lookup is not None else None
        if rec is not None:
            try:
                f = open(rec["path"], "rb")
            except OSError:
                return None
            o, ln = self._clamp(rec["size"], off, length)
            if o:
                try:
                    f.seek(o)
                except OSError:
                    f.close()
                    return None
            return rec["meta"], rec["size"], ln, _file_chunks(f, chunk, ln)
        return None

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:
            pass


def _view_chunks(data: memoryview, chunk: int):
    for off in range(0, len(data), chunk):
        yield data[off:off + chunk]


def _file_chunks(f, chunk: int, limit: Optional[int] = None):
    try:
        left = limit
        while True:
            want = chunk if left is None else min(chunk, left)
            if want <= 0:
                return
            piece = f.read(want)
            if not piece:
                return
            if left is not None:
                left -= len(piece)
            yield piece
    finally:
        f.close()


def _client_with_deadline(addr: Tuple[str, int], authkey: bytes,
                          timeout: float):
    """Client() with a bounded connect+handshake.

    A SIGSTOPped/hung peer ACCEPTS the TCP connection (kernel backlog)
    and then never answers the HMAC challenge — a plain Client() blocks
    forever inside answer_challenge, before any per-chunk deadline can
    apply.  The handshake runs on a helper thread; past the deadline the
    attempt is abandoned (the thread closes the socket if it ever
    completes) and the caller's retry/failover takes over."""
    if not timeout or timeout <= 0:
        return Client(tuple(addr), family="AF_INET", authkey=authkey)
    box: dict = {}
    lock = threading.Lock()
    done = threading.Event()

    def run():
        try:
            c = Client(tuple(addr), family="AF_INET", authkey=authkey)
        except BaseException as e:  # noqa: BLE001 — forwarded to caller
            with lock:
                box["err"] = e
            done.set()
            return
        with lock:
            if box.get("abandoned"):
                abandoned = True
            else:
                box["conn"] = c
                abandoned = False
        done.set()
        if abandoned:
            try:
                c.close()
            except Exception:
                pass

    threading.Thread(target=run, name="rtpu-xfer-conn", daemon=True).start()
    if not done.wait(timeout):
        with lock:
            conn = box.get("conn")
            if conn is None:
                box["abandoned"] = True
        if box.get("abandoned"):
            raise OSError(
                f"transfer connect to {addr} stalled past {timeout}s")
        return conn
    if "err" in box:
        raise box["err"]
    return box["conn"]


class TransferClient:
    """Pulls objects from remote transfer servers; caches connections."""

    def __init__(self, authkey: bytes):
        self.authkey = authkey
        self._conns = {}
        self._conn_locks = {}  # addr -> per-connection stream lock
        self._lock = threading.Lock()  # guards the two maps only
        # Per-peer bandwidth/load accounting: EWMA bytes/s per source and
        # a live in-flight stream count, feeding striped range assignment
        # and get_many's least-loaded holder choice.
        self._peer_bw: Dict[tuple, float] = {}
        self._peer_active: Dict[tuple, int] = {}
        self._peer_lock = threading.Lock()

    # ---- per-peer accounting ----
    def _stream_begin(self, addr: tuple) -> None:
        with self._peer_lock:
            self._peer_active[addr] = self._peer_active.get(addr, 0) + 1
        _stat_add("active_streams", 1)

    def _stream_end(self, addr: tuple, nbytes: int, dt: float) -> None:
        with self._peer_lock:
            n = self._peer_active.get(addr, 1) - 1
            if n <= 0:
                self._peer_active.pop(addr, None)
            else:
                self._peer_active[addr] = n
            if nbytes > 0 and dt > 0:
                bw = nbytes / dt
                old = self._peer_bw.get(addr)
                self._peer_bw[addr] = \
                    bw if old is None else 0.7 * old + 0.3 * bw
        _stat_add("active_streams", -1)
        if nbytes > 0:
            try:
                _peer_meter(f"{addr[0]}:{addr[1]}").mark(nbytes)
            except Exception:
                pass

    def peer_bandwidth(self, addr) -> float:
        with self._peer_lock:
            return self._peer_bw.get(tuple(addr), 0.0)

    def rank_sources(self, addrs) -> list:
        """Order candidate holders least-loaded-first: fewest in-flight
        streams from this process, then highest observed bandwidth.
        Unmeasured peers sort ahead of known-slow ones (optimism spreads
        first touches across holders)."""
        with self._peer_lock:
            def key(a):
                t = tuple(a)
                return (self._peer_active.get(t, 0),
                        -self._peer_bw.get(t, float("inf")))

            return sorted(addrs, key=key)

    def _conn_for(self, addr: Tuple[str, int]):
        addr = tuple(addr)
        with self._lock:
            conn = self._conns.get(addr)
            lock = self._conn_locks.setdefault(addr, threading.Lock())
        if conn is not None:
            return conn, lock
        from ray_tpu._private.config import CONFIG

        conn = _client_with_deadline(addr, self.authkey,
                                     float(CONFIG.transfer_timeout_s))
        with self._lock:
            old = self._conns.setdefault(addr, conn)
        if old is not conn:
            conn.close()
            return old, lock
        return conn, lock

    @staticmethod
    def _await_bytes(conn, timeout_s: float, oid: ObjectID, what: str):
        """Per-chunk progress deadline: a stream that stops moving raises
        instead of blocking recv() forever (a severed peer whose FIN was
        lost looks exactly like a slow one — bound it)."""
        if timeout_s and timeout_s > 0 and not conn.poll(timeout_s):
            raise OSError(
                f"transfer of {oid} stalled: no {what} for {timeout_s}s")

    def _invalidate(self, addr):
        with self._lock:
            conn = self._conns.pop(tuple(addr), None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def pull(self, addr: Tuple[str, int], oid: ObjectID,
             sink=None) -> Tuple[bytes, bytes]:
        """Fetch (meta, data) for oid from the store at addr.

        If `sink` (a writable buffer of the right size, e.g. a local shm
        view) is provided, chunks are written into it and `data` returns
        that buffer's bytes are NOT copied again — the caller owns sink.
        Connection errors/stalls invalidate the cached conn and retry
        with backoff (`transfer_retries`); each chunk must arrive within
        `transfer_timeout_s` or the attempt counts as failed."""
        addr_t = tuple(addr)
        t0 = time.monotonic()
        nbytes = 0
        self._stream_begin(addr_t)
        try:
            meta, data = self._pull_impl(addr, oid, sink)
            nbytes = len(data) if data is not None else (
                len(memoryview(sink)) if sink is not None else 0)
            return meta, data
        finally:
            self._stream_end(addr_t, nbytes, time.monotonic() - t0)

    def _pull_impl(self, addr: Tuple[str, int], oid: ObjectID,
                   sink=None) -> Tuple[bytes, bytes]:
        from ray_tpu._private.chaos import net_fault
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.retry import RetryPolicy

        retries = max(0, int(CONFIG.transfer_retries))
        timeout_s = float(CONFIG.transfer_timeout_s)
        policy = RetryPolicy(base=0.05, cap=1.0)
        tc = None
        try:
            from ray_tpu import observability as obs
            from ray_tpu.util.tracing import tracing_enabled

            if tracing_enabled():
                tc = obs.get_context()
        except Exception:
            pass
        for attempt in range(retries + 1):
            act = net_fault("pull")
            if act is not None:
                kind, delay_ms = act
                if kind == "delay":
                    time.sleep(delay_ms / 1000.0)
                elif kind in ("drop", "sever"):
                    # The data channel is strict request/response: a lost
                    # frame is indistinguishable from a severed conn, so
                    # both surface as a connection failure (and retry).
                    self._invalidate(addr)
                    if attempt >= retries:
                        raise OSError("chaos: transfer connection severed")
                    time.sleep(policy.delay(attempt + 1))
                    continue
            conn, conn_lock = self._conn_for(addr)
            try:
                # One in-flight request per CONNECTION (request/response
                # protocol); pulls against different servers overlap.
                with conn_lock:
                    req = {"oid": oid.binary()}
                    if tc is not None:
                        req["tc"] = tc
                    conn.send(req)
                    self._await_bytes(conn, timeout_s, oid, "header")
                    hdr = conn.recv()
                    if not hdr["ok"]:
                        raise KeyError(hdr["error"])
                    size = hdr["size"]
                    if sink is not None:
                        view = memoryview(sink)
                        off = 0
                        if size == 0:
                            self._await_bytes(conn, timeout_s, oid, "chunk")
                            conn.recv_bytes()
                        while off < size:
                            self._await_bytes(conn, timeout_s, oid, "chunk")
                            n = conn.recv_bytes_into(view[off:])
                            off += n
                        return hdr["meta"], None
                    parts = []
                    got = 0
                    while got < size:
                        self._await_bytes(conn, timeout_s, oid, "chunk")
                        b = conn.recv_bytes()
                        parts.append(b)
                        got += len(b)
                    if size == 0:
                        self._await_bytes(conn, timeout_s, oid, "chunk")
                        conn.recv_bytes()
                    return hdr["meta"], b"".join(parts)
            except (EOFError, OSError, BrokenPipeError):
                self._invalidate(addr)
                if attempt >= retries:
                    raise
                time.sleep(policy.delay(attempt + 1))
        raise RuntimeError("unreachable")

    def pull_range(self, addr: Tuple[str, int], oid: ObjectID, off: int,
                   length: int, sink, tc=None,
                   retries: Optional[int] = None) -> Tuple[bytes, int]:
        """Fetch bytes [off, off+length) of oid from addr into ``sink``
        (a writable view of exactly that span).  Returns (meta, nbytes).

        Retries are PER RANGE: a dropped/severed frame re-requests only
        this range over a fresh connection — the other ranges of a
        striped pull are untouched.  Raises RangeUnavailableError when
        the peer holds the object but not this range (partial holder the
        directory over-promised): the caller reassigns the range without
        counting the peer dead for other work."""
        from ray_tpu._private.chaos import net_fault
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.retry import RetryPolicy

        if retries is None:
            retries = max(0, int(CONFIG.transfer_retries))
        timeout_s = float(CONFIG.transfer_timeout_s)
        policy = RetryPolicy(base=0.05, cap=1.0)
        addr = tuple(addr)
        t0 = time.monotonic()
        done = 0
        self._stream_begin(addr)
        try:
            for attempt in range(retries + 1):
                act = net_fault("pull")
                if act is not None:
                    kind, delay_ms = act
                    if kind == "delay":
                        time.sleep(delay_ms / 1000.0)
                    elif kind in ("drop", "sever"):
                        self._invalidate(addr)
                        if attempt >= retries:
                            raise OSError(
                                "chaos: transfer connection severed")
                        _stat_add("range_retries")
                        time.sleep(policy.delay(attempt + 1))
                        continue
                conn, conn_lock = self._conn_for(addr)
                try:
                    with conn_lock:
                        req = {"oid": oid.binary(), "off": int(off),
                               "len": int(length)}
                        if tc is not None:
                            req["tc"] = tc
                        conn.send(req)
                        self._await_bytes(conn, timeout_s, oid, "header")
                        hdr = conn.recv()
                        if not hdr["ok"]:
                            if hdr.get("code") == "norange":
                                raise RangeUnavailableError(hdr["error"])
                            raise KeyError(hdr["error"])
                        want = max(0, min(int(length),
                                          int(hdr["size"]) - int(off)))
                        view = memoryview(sink)
                        got = 0
                        if want == 0:
                            self._await_bytes(conn, timeout_s, oid,
                                              "chunk")
                            conn.recv_bytes()
                        while got < want:
                            self._await_bytes(conn, timeout_s, oid,
                                              "chunk")
                            got += conn.recv_bytes_into(view[got:])
                        done = got
                        return hdr["meta"], got
                except (EOFError, OSError, BrokenPipeError):
                    self._invalidate(addr)
                    if attempt >= retries:
                        raise
                    _stat_add("range_retries")
                    time.sleep(policy.delay(attempt + 1))
            raise RuntimeError("unreachable")
        finally:
            self._stream_end(addr, done, time.monotonic() - t0)

    def close(self):
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except Exception:
                    pass
            self._conns.clear()


class _Source:
    __slots__ = ("addr", "chunks", "dead", "spawned")

    def __init__(self, addr: tuple, chunks: Optional[Set[int]]):
        self.addr = tuple(addr)
        self.chunks = chunks  # None == full holder
        self.dead = False
        self.spawned = False


def pull_striped(client: TransferClient, oid: ObjectID, size: int,
                 sources, sink, *, meta_hint: Optional[bytes] = None,
                 chunk: Optional[int] = None, tc=None, refresh=None,
                 progress=None) -> Tuple[Optional[bytes], dict]:
    """Multi-source pull: split [0, size) into chunk-aligned ranges and
    fetch them concurrently from every live source, writing each range
    into its slice of ``sink`` (one preallocated destination buffer).

    ``sources`` is an iterable of (addr, chunk_index_set_or_None) — None
    marks a full holder, a set marks a partial (cooperative) holder that
    can only be assigned ranges its bitmap covers.  Work-stealing: each
    source's stream claims the next range it is eligible for, so fast
    peers naturally carry more ranges and per-peer bandwidth accounting
    (rank_sources) decides which sources stream at all when there are
    more holders than ``transfer_stripe_sources``.

    Failure model (the PR 7 failover, made per-range): a dead/stalled
    source's claimed range is requeued and reassigned to a surviving
    source; ``refresh()`` (optional, called when sources run dry or there
    is spare stream capacity) re-asks the directory for holders so
    newly-advertised partial holders join MID-pull.  Raises the last
    source error only when no source can finish the job.

    ``progress(off, length)`` fires after each landed range — the hook
    cooperative pullers use to advertise their own bitmap.

    Returns (meta, stats); meta falls back to ``meta_hint`` when every
    source that answered was itself meta-less (an in-progress partial).
    """
    from ray_tpu._private.config import CONFIG

    chunkb = int(chunk or _chunk_size()) or CHUNK
    nchunks = max(1, (size + chunkb - 1) // chunkb)
    max_src = max(1, int(CONFIG.transfer_stripe_sources))
    target = max(2, int(CONFIG.transfer_stripe_ranges))
    nranges = min(nchunks, max(target, 2 * max_src))
    per, extra = divmod(nchunks, nranges)
    bounds: List[Tuple[int, int]] = []
    lo = 0
    for i in range(nranges):
        hi = lo + per + (1 if i < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    # Rotate the claim order per puller: concurrent pullers of the same
    # object then land DIFFERENT ranges first, so their partial bitmaps
    # are useful to each other (the dissemination-mesh property; a fixed
    # 0..N order would make every peer's bitmap a prefix of your own).
    start = random.randrange(nranges)
    pending: List[int] = [(start + i) % nranges for i in range(nranges)]
    claimed: Set[int] = set()
    done: Set[int] = set()
    cond = threading.Condition()
    srcs: Dict[tuple, _Source] = {}
    meta_box: List[Optional[bytes]] = [None]
    err_box: List[Optional[BaseException]] = [None]
    abort = [False]
    stats = {"nranges": nranges, "partial_ranges": 0, "reassigned": 0,
             "bytes_from": {}, "refreshes": 0}
    sinkview = memoryview(sink)
    timeout_s = float(CONFIG.transfer_timeout_s) or 120.0

    def _merge(items) -> int:
        """Fold (addr, chunks) pairs into the source table (under cond).
        Returns how many NEW usable sources appeared."""
        fresh = 0
        for addr, chunks in items:
            key = tuple(addr)
            cur = srcs.get(key)
            if cur is None:
                srcs[key] = _Source(key, set(chunks)
                                    if chunks is not None else None)
                fresh += 1
            elif cur.chunks is not None:
                if chunks is None:
                    cur.chunks = None  # promoted to full holder
                else:
                    cur.chunks.update(chunks)
        return fresh

    def _eligible(src: _Source, ridx: int) -> bool:
        if src.chunks is None:
            return True
        rlo, rhi = bounds[ridx]
        return all(i in src.chunks for i in range(rlo, rhi))

    def _runner(src: _Source):
        try:
            while True:
                with cond:
                    if abort[0] or src.dead or len(done) == nranges:
                        return
                    ridx = next((r for r in pending
                                 if _eligible(src, r)), None)
                    if ridx is None:
                        if not pending and not claimed:
                            return
                        cond.wait(0.05)  # a failure may requeue a range
                        continue
                    pending.remove(ridx)
                    claimed.add(ridx)
                rlo, rhi = bounds[ridx]
                off = rlo * chunkb
                ln = min(size, rhi * chunkb) - off
                seg = sinkview[off:off + ln]
                ok = False
                try:
                    m, n = client.pull_range(src.addr, oid, off, ln, seg,
                                             tc=tc)
                    ok = True
                except BaseException as e:  # noqa: BLE001 — requeue+record
                    with cond:
                        claimed.discard(ridx)
                        pending.append(ridx)
                        src.dead = True
                        err_box[0] = e
                        stats["reassigned"] += 1
                        cond.notify_all()
                    _stat_add("range_reassignments")
                    return
                finally:
                    seg.release()
                with cond:
                    claimed.discard(ridx)
                    done.add(ridx)
                    if m is not None and meta_box[0] is None:
                        meta_box[0] = m
                    key = f"{src.addr[0]}:{src.addr[1]}"
                    stats["bytes_from"][key] = \
                        stats["bytes_from"].get(key, 0) + n
                    if src.chunks is not None:
                        stats["partial_ranges"] += 1
                        _stat_add("ranges_from_partial")
                    cond.notify_all()
                _stat_add("ranges_completed")
                _stat_add("striped_bytes", ln)
                if progress is not None:
                    try:
                        progress(off, ln)
                    except Exception:
                        pass
        finally:
            with cond:
                src.spawned = False
                cond.notify_all()

    def _spawn_locked() -> None:
        live = sum(1 for s in srcs.values() if s.spawned)
        if live >= max_src:
            return
        idle = [s for s in srcs.values() if not s.dead and not s.spawned]
        for addr in client.rank_sources([s.addr for s in idle]):
            if live >= max_src:
                return
            s = srcs[tuple(addr)]
            s.spawned = True
            live += 1
            threading.Thread(target=_runner, args=(s,),
                             name="rtpu-stripe", daemon=True).start()

    _stat_add("striped_pulls")
    with cond:
        _merge(sources)
        _spawn_locked()
    last_progress = time.monotonic()
    last_refresh = 0.0
    refresh_strikes = 0
    refresh_interval = 0.05
    ndone = 0
    try:
        while True:
            with cond:
                cond.wait(0.05)
                if len(done) > ndone:
                    ndone = len(done)
                    last_progress = time.monotonic()
                if len(done) == nranges:
                    return (meta_box[0] if meta_box[0] is not None
                            else meta_hint), stats
                _spawn_locked()  # replace streams lost to dead sources
                alive = [s for s in srcs.values() if not s.dead]
                spawned = any(s.spawned for s in srcs.values())
            now = time.monotonic()
            want_refresh = refresh is not None and (
                not alive or len(alive) < max_src)
            if want_refresh and now - last_refresh >= refresh_interval:
                last_refresh = now
                stats["refreshes"] += 1
                try:
                    extra_sources = refresh() or []
                except Exception:
                    extra_sources = []
                with cond:
                    if _merge(extra_sources):
                        refresh_strikes = 0
                        refresh_interval = 0.05
                    else:
                        # Nothing new: poll the directory less and less
                        # (it answers every puller of a hot broadcast).
                        refresh_interval = min(1.0, refresh_interval * 2)
                        if not alive:
                            refresh_strikes += 1
                    if alive:
                        refresh_strikes = 0
                    _spawn_locked()
            if not alive and not spawned:
                if refresh is None or refresh_strikes >= 3:
                    raise err_box[0] or OSError(
                        f"striped pull of {oid}: no live sources")
            if now - last_progress > timeout_s:
                raise err_box[0] or OSError(
                    f"striped pull of {oid} stalled: no range completed "
                    f"for {timeout_s}s")
    finally:
        with cond:
            abort[0] = True
            cond.notify_all()
            # Runner threads hold live views into sinkview while a range
            # is in flight; the caller may unlink/close the backing shm
            # the moment we return, so drain them first (bounded by the
            # per-chunk progress deadline inside pull_range).
            deadline = time.monotonic() + timeout_s + 5.0
            while any(s.spawned for s in srcs.values()) \
                    and time.monotonic() < deadline:
                cond.wait(0.2)
        sinkview.release()
