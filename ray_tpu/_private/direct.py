"""Direct transports: the task/actor hot path without the head.

The reference keeps steady-state submission out of its control plane:
callers cache worker leases per scheduling class and push tasks straight
to the leased worker (core_worker/transport/direct_task_transport.h:57,
direct_task_transport.cc:380), actor calls ride a per-caller connection
to the actor's dedicated worker (direct_actor_task_submitter.h:67), and
the CALLER owns its tasks' results — holding them in an in-process
memory store (memory_store.h:43) with a borrowing protocol for refs that
travel to other processes (reference_count.h:61,520).

ray_tpu equivalent, one module:

  - ``OwnedStore``     owner-authoritative in-process object table
  - ``DirectServer``   per-process listener serving exec / fetch / pin
  - ``DirectChannel``  client side of one direct connection
  - ``DirectSubmitter``lease cache + per-actor channels + borrow pins

The head stays authoritative for placement (lease grants), the actor
restart FSM, large objects (shm store + directory) and everything the
classic path still carries: non-DEFAULT scheduling strategies, placement
groups, and any submission the direct path cannot take right now — every
direct failure falls back to the classic head path, never errors out.

Ownership rules (mirroring reference_count.h):
  - The submitter owns task returns and put objects small enough to stay
    inline; entries live in its OwnedStore.
  - A ref serialized to another process carries the owner's address; the
    receiving process is a *borrower*: it registers a pin with the owner
    for as long as it holds local refs (the WaitForRefRemoved handshake
    collapses to this pin/unpin pair; a broken borrower connection drops
    its pins, like the reference's borrower-death cleanup).
  - An object whose bytes moved to the shared store (large results) or
    to the head (classic fallback) is EXTERN: resolution falls through
    to the head directory, and the owner mirrors its local refcount to
    the head so the head's lifecycle rules apply.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.task_spec import TaskSpec, TaskType

# Owned-entry states.
PENDING = 0   # task in flight; bytes not produced yet
READY = 1     # inline bytes held here
ERROR = 2     # serialized exception held here
EXTERN = 3    # bytes live in the shared store / head directory

FETCH_WAIT_S = 120.0  # safety valve on deferred fetch replies


class _Entry:
    __slots__ = ("state", "meta", "data", "refs", "pins", "waiters",
                 "promote", "linked")

    def __init__(self):
        self.state = PENDING
        self.meta: Optional[bytes] = None
        self.data: Optional[bytes] = None
        self.refs = 0               # local ObjectRef count in the owner
        self.pins: Optional[Dict[bytes, int]] = None  # token -> count
        self.waiters: Optional[List[Callable]] = None  # deferred fetch replies
        self.promote = False        # promote to head on fulfill (classic arg)
        # Contained-ref pins released when THIS entry is freed:
        # (res-token, [(oid binary, owner addr), ...]).
        self.linked = None


class OwnedStore:
    """Owner-side object table.  An entry is dropped once it has no local
    refs, no pins, and is past PENDING (a pending entry with no holders is
    kept as a tombstone until its task completes, then dropped).

    Blocking waits share one condition variable (hot path: entries are
    created per task — a per-entry Event would cost more than the entry)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._nwaiters = 0
        self._entries: Dict[ObjectID, _Entry] = {}
        # Linked-pin descriptors of freed entries, drained by the
        # submitter's maintenance loop (released OUTSIDE the store lock —
        # the release sends on channels whose locks order after ours).
        self.released_links: deque = deque()

    # ---- lifecycle ----
    def create_pending(self, oid: ObjectID) -> None:
        """Create a pending entry holding ONE submission ref: the ObjectRef
        the submit call returns adopts it (ObjectRef construction races the
        task's completion — without the pre-held ref, a fast result could be
        freed before the ref exists)."""
        with self._lock:
            if oid not in self._entries:
                e = self._entries[oid] = _Entry()
                e.refs = 1

    def put(self, oid: ObjectID, meta: bytes, data: bytes) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                e = self._entries[oid] = _Entry()
            e.meta, e.data = meta, data
            e.state = READY  # publish AFTER the bytes (unlocked readers)
            if self._nwaiters:
                self._cond.notify_all()

    def put_with_ref(self, oid: ObjectID, meta: bytes, data: bytes) -> None:
        """put() + the first local ref in ONE lock round trip — the small-
        put hot path (the caller constructs its ObjectRef with
        skip_adding_local_ref and marks it owner-registered)."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                e = self._entries[oid] = _Entry()
            e.meta, e.data = meta, data
            e.refs += 1
            e.state = READY  # publish AFTER the bytes (unlocked readers)
            if self._nwaiters:
                self._cond.notify_all()

    def wait_fulfilled(self, e: _Entry, timeout: Optional[float]) -> bool:
        """Block until `e` leaves PENDING.  False on timeout."""
        with self._cond:
            if e.state != PENDING:
                return True
            self._nwaiters += 1
            try:
                return self._cond.wait_for(lambda: e.state != PENDING,
                                           timeout)
            finally:
                self._nwaiters -= 1

    def _fire(self, e: _Entry):
        if self._nwaiters:
            self._cond.notify_all()
        if not e.waiters:
            return
        waiters, e.waiters = e.waiters, None
        for cb in waiters:
            try:
                cb(e)
            except Exception:
                pass

    def fulfill_inline(self, oid: ObjectID, meta: bytes, data: bytes) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return
            e.meta, e.data = meta, data
            e.state = READY  # publish AFTER the bytes (unlocked readers)
            self._fire(e)
            self._maybe_free(oid, e)

    def fulfill_error(self, oid: ObjectID, meta: bytes, data: bytes) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return
            e.meta, e.data = meta, data
            e.state = ERROR  # publish AFTER the bytes (unlocked readers)
            self._fire(e)
            self._maybe_free(oid, e)

    def make_extern(self, oid: ObjectID) -> Tuple[bool, bool]:
        """Transition to EXTERN.  Returns (had_entry, has_local_refs) so the
        caller can mirror its refcount to the head directory."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return False, False
            # Bytes are deliberately RETAINED: an unlocked reader that
            # already observed READY must still find valid meta/data (the
            # head holds an identical copy from promotion/seal).
            e.state = EXTERN
            self._fire(e)
            refs = e.refs > 0
            self._maybe_free(oid, e)
            return True, refs

    def set_promote_on_fulfill(self, oid: ObjectID) -> bool:
        """Classic-fallback submit referenced a PENDING owned object: ask the
        owner loop to promote it to the head when the bytes arrive."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or e.state != PENDING:
                return False
            e.promote = True
            return True

    def take_promote(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.promote:
                return False
            e.promote = False
            return True

    # ---- refs & pins ----
    def lookup(self, oid: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(oid)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def add_ref(self, oid: ObjectID) -> Optional[Tuple[int, int]]:
        """Returns (new_count, state) if this process owns the entry."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return None
            e.refs += 1
            return e.refs, e.state

    def remove_ref(self, oid: ObjectID) -> Optional[Tuple[int, int]]:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return None
            e.refs -= 1
            n, state = e.refs, e.state
            self._maybe_free(oid, e)
            return n, state

    def pin(self, oid: ObjectID, token: bytes) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                if e.pins is None:
                    e.pins = {}
                e.pins[token] = e.pins.get(token, 0) + 1

    def unpin(self, oid: ObjectID, token: bytes) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is None or e.pins is None:
                return
            n = e.pins.get(token, 0) - 1
            if n <= 0:
                e.pins.pop(token, None)
            else:
                e.pins[token] = n
            self._maybe_free(oid, e)

    def set_linked(self, oid: ObjectID, linked) -> bool:
        """Attach contained-ref pins to an entry; False if already freed
        (caller releases the pins immediately)."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return False
            e.linked = linked
            return True

    def _maybe_free(self, oid: ObjectID, e: _Entry) -> None:
        if e.refs <= 0 and not e.pins and e.state != PENDING \
                and not e.waiters and not e.promote:
            self._entries.pop(oid, None)
            if e.linked is not None:
                self.released_links.append(e.linked)
                e.linked = None

    # ---- fetch serving (deferred replies: reference pubsub-on-ready) ----
    def fetch_or_wait(self, oid: ObjectID, respond: Callable,
                      nowait: bool = False) -> None:
        """respond(kind, meta, data) now or when the entry is fulfilled.
        With nowait, a PENDING entry answers "pending" immediately (used by
        direct-task arg resolution, which must never block a lease queue —
        see _deps_resolved)."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                respond("missing", None, None)
                return
            if e.state == PENDING:
                if nowait:
                    respond("pending", None, None)
                    return
                if e.waiters is None:
                    e.waiters = []
                e.waiters.append(lambda ent: respond(
                    {READY: "bytes", ERROR: "error", EXTERN: "extern"}.get(
                        ent.state, "missing"), ent.meta, ent.data))
                return
            kind = {READY: "bytes", ERROR: "error", EXTERN: "extern"}[e.state]
            respond(kind, e.meta, e.data)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "pending": sum(1 for e in self._entries.values()
                                   if e.state == PENDING)}


# ---------------------------------------------------------------------------
# Endpoint helpers
# ---------------------------------------------------------------------------
_machine_id_cache: Optional[str] = None


def machine_id() -> str:
    """Identity of the physical machine (NOT the logical ray_tpu "host":
    several node agents with distinct host keys may share one box — the
    virtual multi-host test substrate, and co-located agents in prod).
    Used to decide whether an advertised loopback TCP endpoint is
    actually reachable."""
    global _machine_id_cache
    if _machine_id_cache is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _machine_id_cache = f.read().strip()
        except OSError:
            import uuid

            _machine_id_cache = f"node-{uuid.getnode():x}"
    return _machine_id_cache


def pick_endpoint(addr: Optional[dict], my_host_key: str) -> Optional[tuple]:
    """Choose a reachable endpoint from an advertised address dict
    {"hk": host_key, "mid": machine id, "unix": path|None,
    "tcp": (host, port)|None}.  A loopback TCP endpoint is reachable
    from a different logical host only when both live on the same
    physical machine (owner fetches from co-located node agents — e.g.
    weight-broadcast refs consumed by rollout actors on sibling nodes)."""
    if not addr:
        return None
    same_host = addr.get("hk") == my_host_key
    if same_host and addr.get("unix"):
        return ("unix", addr["unix"])
    tcp = addr.get("tcp")
    if tcp is not None:
        host = tcp[0]
        loopback = host.startswith("127.") or host in ("localhost", "::1")
        if same_host or not loopback \
                or addr.get("mid") == machine_id():
            return ("tcp", (host, int(tcp[1])))
    return None


def _connect(endpoint: tuple, authkey: bytes):
    from multiprocessing.connection import Client

    if endpoint[0] == "unix":
        return Client(endpoint[1], family="AF_UNIX", authkey=authkey)
    return Client(tuple(endpoint[1]), family="AF_INET", authkey=authkey)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------
class DirectServer:
    """Per-process direct listener.  Serves:
      exec   — push a TaskSpec for execution (workers only)
      fetch  — read an owned object (deferred until fulfilled)
      pin    — borrow registration (dropped when the conn dies)
      cancel — drop a queued direct task
    """

    def __init__(self, owned: OwnedStore, authkey: bytes, host_key: str,
                 session_dir: Optional[str] = None,
                 on_exec: Optional[Callable] = None,
                 tcp_bind: Optional[str] = None):
        from multiprocessing.connection import Listener

        self.owned = owned
        self.authkey = authkey
        self.on_exec = on_exec
        self.cancelled: set = set()
        self._shutdown = False
        self._listeners = []
        addr: Dict[str, Any] = {"hk": host_key, "mid": machine_id()}
        if session_dir:
            os.makedirs(session_dir, exist_ok=True)
            path = os.path.join(session_dir,
                                f"dx-{os.urandom(6).hex()}.sock")
            lsn = Listener(path, family="AF_UNIX", authkey=authkey)
            self._listeners.append(lsn)
            addr["unix"] = path
        if tcp_bind is not None:
            lsn = Listener((tcp_bind, 0), family="AF_INET", authkey=authkey)
            self._listeners.append(lsn)
            port = lsn.address[1]
            if tcp_bind in ("0.0.0.0", "::"):
                from ray_tpu._private.transfer import routable_ip

                addr["tcp"] = (routable_ip(), port)
            else:
                addr["tcp"] = (tcp_bind, port)
        self.address = addr
        for lsn in self._listeners:
            threading.Thread(target=self._accept_loop, args=(lsn,),
                             name="rtpu-direct-accept", daemon=True).start()

    def _accept_loop(self, listener):
        while not self._shutdown:
            try:
                conn = listener.accept()
            except Exception:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="rtpu-direct-conn", daemon=True).start()

    def send_on(self, conn, msg) -> bool:
        lock = getattr(conn, "_dx_lock", None)
        try:
            if lock is not None:
                with lock:
                    conn.send(msg)
            else:
                conn.send(msg)
            return True
        except Exception:
            return False

    def _serve(self, conn):
        conn._dx_lock = threading.Lock()
        conn_pins: List[Tuple[ObjectID, bytes]] = []
        try:
            while True:
                msg = conn.recv()
                t = msg.get("t")
                if t == "exec":
                    if self.on_exec is not None:
                        self.on_exec(msg["spec"], conn)
                elif t == "execb":
                    if self.on_exec is not None:
                        for spec in msg["specs"]:
                            self.on_exec(spec, conn)
                elif t == "fetch":
                    oid = ObjectID(msg["oid"])
                    mid = msg["mid"]

                    def respond(kind, meta, data, _mid=mid, _conn=conn):
                        self.send_on(_conn, {"t": "fetch_r", "mid": _mid,
                                             "k": kind, "m": meta, "d": data})

                    self.owned.fetch_or_wait(oid, respond,
                                             nowait=bool(msg.get("nw")))
                elif t == "pin":
                    oid, tok = ObjectID(msg["oid"]), msg["tok"]
                    self.owned.pin(oid, tok)
                    conn_pins.append((oid, tok))
                elif t == "unpin":
                    oid, tok = ObjectID(msg["oid"]), msg["tok"]
                    self.owned.unpin(oid, tok)
                    try:
                        conn_pins.remove((oid, tok))
                    except ValueError:
                        pass
                elif t == "cancel":
                    self.cancelled.add(TaskID(msg["task_id"]))
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            import traceback

            traceback.print_exc()
        finally:
            # Borrower died: its pins fall away (reference: borrower-death
            # cleanup in the ownership protocol).
            for oid, tok in conn_pins:
                self.owned.unpin(oid, tok)
            try:
                conn.close()
            except Exception:
                pass

    def shutdown(self):
        self._shutdown = True
        for lsn in self._listeners:
            try:
                lsn.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Channel (client side)
# ---------------------------------------------------------------------------
class DirectChannel:
    """Client side of one direct connection.

    Exec pushes go through a sender thread with natural micro-batching:
    whatever accumulated while the previous send was on the wire goes out
    as ONE `execb` frame (one pickle, one write) — burst submission costs
    ~1 syscall per dozen tasks with no added latency when idle (the same
    shape as the reference's batched gRPC task pushes)."""

    def __init__(self, endpoint: tuple, authkey: bytes,
                 on_done: Optional[Callable] = None,
                 on_close: Optional[Callable] = None):
        self.endpoint = endpoint
        self.conn = _connect(endpoint, authkey)
        self.alive = True
        self.on_done = on_done
        self.on_close = on_close
        self._send_lock = threading.Lock()
        self._futs: Dict[int, Future] = {}
        self._futs_lock = threading.Lock()
        self._mid = 0
        # Function blobs already shipped on this channel (keyed by hash):
        # later execs strip the blob and the worker loads from its cache
        # (reference: the function table — functions ship once per worker,
        # not once per task).
        self.sent_funcs: set = set()
        self._outq: deque = deque()
        self._out_cond = threading.Condition()
        self._close_fired = False
        self._close_lock = threading.Lock()
        self._reader = threading.Thread(target=self._read_loop,
                                        name="rtpu-direct-chan", daemon=True)
        self._reader.start()
        self._sender = threading.Thread(target=self._send_loop,
                                        name="rtpu-direct-send", daemon=True)
        self._sender.start()

    def _read_loop(self):
        try:
            while True:
                msg = self.conn.recv()
                t = msg.get("t")
                if t == "doneb":
                    if self.on_done is not None:
                        for m in msg["dones"]:
                            self.on_done(m)
                elif t == "done":
                    if self.on_done is not None:
                        self.on_done(msg)
                elif t == "fetch_r":
                    with self._futs_lock:
                        fut = self._futs.pop(msg["mid"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            import traceback

            traceback.print_exc()
        finally:
            self._fire_close()

    def _fire_close(self):
        self.alive = False
        with self._close_lock:
            if self._close_fired:
                return
            self._close_fired = True
        with self._out_cond:
            self._out_cond.notify_all()
        with self._futs_lock:
            futs, self._futs = list(self._futs.values()), {}
        for fut in futs:
            if not fut.done():
                fut.set_exception(
                    exc.RayTpuError("direct connection closed"))
        if self.on_close is not None:
            try:
                self.on_close(self)
            except Exception:
                pass

    def _send_loop(self):
        while True:
            with self._out_cond:
                while not self._outq and self.alive:
                    self._out_cond.wait()
                if not self.alive:
                    return
                batch = []
                while self._outq and len(batch) < 128:
                    batch.append(self._outq.popleft())
            import copy as _copy

            wire = []
            for spec in batch:
                h = spec.func_hash
                if spec.func_blob is not None and h is not None:
                    if h in self.sent_funcs:
                        # Strip on a shallow COPY: the original spec may be
                        # re-pickled concurrently by a classic reroute.
                        spec = _copy.copy(spec)
                        spec.func_blob = None
                    else:
                        self.sent_funcs.add(h)
                wire.append(spec)
            msg = ({"t": "exec", "spec": wire[0]} if len(wire) == 1
                   else {"t": "execb", "specs": wire})
            if not self.send(msg):
                self._fire_close()
                return

    def send(self, msg) -> bool:
        try:
            with self._send_lock:
                self.conn.send(msg)
            return True
        except Exception:
            self.alive = False
            return False

    def exec(self, spec: TaskSpec) -> bool:
        """Queue a task push (sender thread delivers; False if the channel
        is already dead — the caller re-routes)."""
        if not self.alive:
            return False
        with self._out_cond:
            self._outq.append(spec)
            self._out_cond.notify()
        return True

    def fetch(self, oid: ObjectID, timeout: Optional[float] = None,
              nowait: bool = False):
        with self._futs_lock:
            self._mid += 1
            mid = self._mid
            fut: Future = Future()
            self._futs[mid] = fut
        msg = {"t": "fetch", "mid": mid, "oid": oid.binary()}
        if nowait:
            msg["nw"] = 1
        if not self.send(msg):
            raise exc.RayTpuError("direct connection closed")
        # timeout=None waits indefinitely: the owner ALWAYS answers a
        # deferred fetch (on fulfill, or the connection breaks on owner
        # death, which surfaces here as an exception).
        return fut.result(timeout=timeout)

    def pin(self, oid: ObjectID, token: bytes) -> bool:
        return self.send({"t": "pin", "oid": oid.binary(), "tok": token})

    def unpin(self, oid: ObjectID, token: bytes) -> bool:
        return self.send({"t": "unpin", "oid": oid.binary(), "tok": token})

    def cancel(self, task_id: TaskID) -> bool:
        return self.send({"t": "cancel", "task_id": task_id.binary()})

    def close(self):
        self.alive = False
        try:
            self.conn.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Submitter (caller side): leases + actor channels + borrows
# ---------------------------------------------------------------------------
class _Lease:
    __slots__ = ("worker_id", "chan", "inflight", "idle_since", "alive")

    def __init__(self, worker_id: bytes, chan: DirectChannel):
        self.worker_id = worker_id
        self.chan = chan
        self.inflight = 0
        self.idle_since = time.monotonic()
        self.alive = True


A_RESOLVING, A_UP, A_CLASSIC = 0, 1, 2


class _ActorClient:
    __slots__ = ("actor_id", "state", "chan", "queue", "inflight",
                 "worker_id")

    def __init__(self, actor_id):
        self.actor_id = actor_id
        self.state = A_RESOLVING
        self.chan: Optional[DirectChannel] = None
        self.queue: deque = deque()      # specs waiting for the channel
        self.inflight: Dict[TaskID, TaskSpec] = {}
        # The incarnation (worker id bytes) this client is connected to:
        # dead-channel reroutes carry it so the head can tell "this call
        # ran on the incarnation that died" from a fresh submission and
        # never replays a budget-exhausted call on a restarted actor.
        self.worker_id: Optional[bytes] = None


class _Inflight:
    __slots__ = ("spec", "lease", "actor", "pins")

    def __init__(self, spec, lease=None, actor=None, pins=None):
        self.spec = spec
        self.lease = lease
        self.actor = actor
        self.pins = pins or []  # list of ("owned"|"owner"|"head", oid, extra)


class DirectSubmitter:
    """Caller-side engine: keeps leases warm per scheduling class, one
    direct channel per actor, in-flight bookkeeping with client-side
    retries, and borrow pins at remote owners."""

    # A lease is considered saturated past this many queued pushes; the
    # submitter then asks for one more lease (grants arrive async).
    _GROW_AT = 2

    def __init__(self, core):
        from ray_tpu._private.config import CONFIG

        self.core = core
        self.owned: OwnedStore = core._owned
        self.host_key = core.host_key
        self.authkey = core.transport.authkey
        self._lock = threading.RLock()
        self._leases: Dict[tuple, List[_Lease]] = {}
        self._lease_req: set = set()       # classes with a grant in flight
        self._lease_backoff: Dict[tuple, float] = {}  # class -> retry-at
        self._actors: Dict[Any, _ActorClient] = {}
        self._fetch_chans: Dict[tuple, DirectChannel] = {}
        self._inflight: Dict[TaskID, _Inflight] = {}
        self._cancelled: set = set()
        self._lease_idle_s = CONFIG.lease_idle_s
        self._closed = False
        self._maint = threading.Thread(target=self._maintenance,
                                       name="rtpu-direct-maint", daemon=True)
        self._maint.start()

    # ================= normal tasks =================
    def _deps_resolved(self, spec: TaskSpec) -> bool:
        """Push only tasks with NO ref dependencies (direct or contained).

        The reference resolves deps before pushing leased tasks
        (LocalDependencyResolver, direct_task_transport.h:40); here any
        dependency-shaped task takes the classic path instead — the head
        dispatches those only to idle workers (a pending dep pushed onto a
        lease queue would block the worker loop and can starve the very
        producer queued behind it), and a worker blocked resolving an arg
        releases its cpu (on_worker_blocked).  Leases carry the high-rate
        independent-task pattern, which is where the head round trip
        actually hurts."""
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if arg.ref is not None or arg.contained:
                return False
        return True

    def submit_task(self, spec: TaskSpec) -> bool:
        """Try to push `spec` over a cached lease.  False → classic path."""
        if (spec.task_type != TaskType.NORMAL
                or spec.scheduling_strategy.kind != "DEFAULT"
                or spec.task_id in self._cancelled):
            return False
        if (spec.args or spec.kwargs) and not self._deps_resolved(spec):
            return False
        key = spec.scheduling_class()
        with self._lock:
            if self._closed:
                return False
            pool = [l for l in self._leases.get(key, []) if l.alive]
            lease = min(pool, key=lambda l: l.inflight, default=None)
            if lease is None or lease.inflight >= self._GROW_AT:
                self._request_lease_async(key, spec)
            if lease is None:
                return False
            pins = self._commit(spec)
            lease.inflight += 1
            self._inflight[spec.task_id.binary()] = _Inflight(
                spec, lease=lease, pins=pins)
        if not lease.chan.exec(spec):
            self._on_chan_close(lease.chan)  # re-route in-flights
        return True

    def _request_lease_async(self, key: tuple, spec: TaskSpec):
        """One outstanding grant request per scheduling class (under lock),
        with a short backoff after a refused grant — a saturated cluster
        must not pay a request thread per submission."""
        if key in self._lease_req or self._closed:
            return
        if time.monotonic() < self._lease_backoff.get(key, 0.0):
            return
        self._lease_req.add(key)
        resources = dict(spec.resources)

        def run():
            granted = None
            try:
                granted = self.core.transport.request(
                    "lease_worker", {"resources": resources})
            except Exception:
                granted = None
            try:
                if not granted:
                    with self._lock:
                        self._lease_backoff[key] = time.monotonic() + 0.05
                if granted:
                    ep = pick_endpoint(granted["addr"], self.host_key)
                    if ep is None:
                        self.core.transport.request_oneway(
                            "return_lease",
                            {"worker_id": granted["worker_id"]})
                        return
                    chan = DirectChannel(ep, self.authkey,
                                         on_done=self._on_done,
                                         on_close=self._on_chan_close)
                    lease = _Lease(granted["worker_id"], chan)
                    with self._lock:
                        if self._closed:
                            chan.close()
                            self.core.transport.request_oneway(
                                "return_lease",
                                {"worker_id": granted["worker_id"]})
                            return
                        self._leases.setdefault(key, []).append(lease)
            except Exception:
                pass
            finally:
                with self._lock:
                    self._lease_req.discard(key)

        threading.Thread(target=run, name="rtpu-lease-req",
                         daemon=True).start()

    # ================= actor tasks =================
    def submit_actor_task(self, spec: TaskSpec) -> bool:
        with self._lock:
            if self._closed:
                return False
            ac = self._actors.get(spec.actor_id)
            if ac is None:
                ac = self._actors[spec.actor_id] = _ActorClient(spec.actor_id)
                self._resolve_actor_async(ac)
            if ac.state == A_CLASSIC:
                return False
            pins = self._commit(spec)
            inf = _Inflight(spec, actor=ac, pins=pins)
            self._inflight[spec.task_id.binary()] = inf
            if ac.state == A_RESOLVING or ac.chan is None:
                ac.queue.append(spec)
                return True
            ac.inflight[spec.task_id.binary()] = spec
            chan = ac.chan
        if not chan.exec(spec):
            self._on_chan_close(chan)
        return True

    def _resolve_actor_async(self, ac: _ActorClient):
        def run():
            chan = None
            # Stale-address window: right after an actor's worker dies, the
            # head may still advertise the old address until its health
            # poll fires.  Re-resolve a few times before giving up on the
            # direct path (the addr request itself blocks while the actor
            # is pending/restarting).
            for attempt in range(20):
                try:
                    got = self.core.transport.request(
                        "actor_direct_addr", {"actor_id": ac.actor_id})
                except BaseException as e:
                    # Actor dead (or head trouble): the head is the
                    # authority — route queued calls through it for
                    # authoritative errors / restart handling.
                    self._actor_to_classic(ac, e)
                    return
                ep = pick_endpoint(got and got.get("addr"), self.host_key)
                if ep is None:
                    self._actor_to_classic(ac, None)
                    return
                ac.worker_id = got.get("worker_id")
                try:
                    chan = DirectChannel(ep, self.authkey,
                                         on_done=self._on_done,
                                         on_close=self._on_chan_close)
                    break
                except Exception:
                    chan = None
                    if self._closed:
                        return
                    time.sleep(0.25)
            if chan is None:
                self._actor_to_classic(ac, None)
                return
            dead = False
            with self._lock:
                # Publish ac.chan FIRST (state stays A_RESOLVING so
                # concurrent submits still queue): if the channel dies at
                # any point — exec returning False mid-drain, or the
                # reader's close callback racing this block —
                # _on_chan_close(chan) must match this actor and replay
                # the specs already moved into ac.inflight; with ac.chan
                # unset they would strand in A_RESOLVING forever.
                ac.chan = chan
                # Enqueue the backlog onto the channel BEFORE exposing
                # A_UP: chan.exec only appends to the sender queue, so a
                # concurrent submit observing A_UP cannot overtake queued
                # calls (per-caller actor ordering).
                while ac.queue:
                    spec = ac.queue.popleft()
                    ac.inflight[spec.task_id.binary()] = spec
                    if not chan.exec(spec):
                        dead = True
                        break
                if not dead:
                    ac.state = A_UP
            if dead:
                self._on_chan_close(chan)
                return

        threading.Thread(target=run, name="rtpu-actor-resolve",
                         daemon=True).start()

    def _actor_to_classic(self, ac: _ActorClient, _err):
        """Hand an actor's queued + future calls to the classic head path.
        Their owned entries flip EXTERN so results (including authoritative
        death errors) resolve through the head.  Drain-then-flip: new calls
        keep queueing (state stays RESOLVING) until the backlog has been
        rerouted, so the head sees them in submission order."""
        while True:
            with self._lock:
                specs = list(ac.queue) + list(ac.inflight.values())
                ac.queue.clear()
                ac.inflight.clear()
                if not specs:
                    ac.state = A_CLASSIC
                    return
            for spec in specs:
                self._reroute_classic(spec, actor=True)

    def _reroute_classic(self, spec: TaskSpec, actor: bool = False,
                         inf: Optional[_Inflight] = None,
                         dead_worker: Optional[bytes] = None):
        if inf is None:
            with self._lock:
                inf = self._inflight.pop(spec.task_id.binary(), None)
        if inf is not None:
            self._release_pins(inf)
        for oid in spec.return_ids():
            self._make_extern_mirrored(oid)
        try:
            self.core._promote_owned_args(spec)
            payload = {"spec": spec}
            if dead_worker is not None:
                # Budget-exhausted call from a dead channel: the head
                # must FAIL it if the actor's incarnation has moved on —
                # re-executing it on the restarted actor would replay a
                # possibly-fatal call the caller already gave up on.
                payload["dead_worker"] = dead_worker
            self.core.transport.request_oneway(
                "actor_call" if actor else "submit", payload)
        except Exception:
            meta, data = _pack_error(exc.RayTpuError(
                "task lost: could not reach the head for fallback"))
            for oid in spec.return_ids():
                self.owned.fulfill_error(oid, meta, data)

    def _make_extern_mirrored(self, oid: ObjectID):
        """EXTERN transition + refcount mirroring to the head directory."""
        had, has_refs = self.owned.make_extern(oid)
        if not had:
            return
        holder = self.core.worker_id.binary()
        try:
            self.core.transport.request_oneway(
                "add_ref", {"oid": oid, "holder": holder})
            if not has_refs:
                self.core.transport.request_oneway(
                    "remove_ref", {"oid": oid, "holder": holder})
        except Exception:
            pass

    # ================= completion =================
    def _on_done(self, msg: dict):
        tid = msg["task_id"]
        with self._lock:
            inf = self._inflight.pop(tid, None)
            if inf is None:
                return
            if inf.lease is not None:
                inf.lease.inflight -= 1
                inf.lease.idle_since = time.monotonic()
            if inf.actor is not None:
                inf.actor.inflight.pop(tid, None)
        spec = inf.spec
        if msg.get("unready"):
            # Worker bounced the push: a dep was still pending at its owner.
            # Re-route through the head (no attempt charge — nothing ran).
            self._reroute_classic(spec, actor=inf.actor is not None, inf=inf)
            return
        error = msg.get("error")
        if (error is not None and spec.retry_exceptions
                and spec.attempt < spec.max_retries
                and spec.task_id not in self._cancelled):
            spec.attempt += 1
            with self._lock:
                self._inflight[tid] = inf  # keep pins across the retry
                resub = False
                chan = None
                if inf.actor is not None and inf.actor.state == A_UP:
                    inf.actor.inflight[tid] = spec
                    chan = inf.actor.chan
                    resub = True
                elif inf.lease is not None and inf.lease.alive:
                    inf.lease.inflight += 1
                    chan = inf.lease.chan
                    resub = True
            if resub and chan.exec(spec):
                return
            with self._lock:
                self._inflight.pop(tid, None)
                if inf.lease is not None and resub:
                    inf.lease.inflight -= 1  # the push we just failed
                if inf.actor is not None:
                    inf.actor.inflight.pop(tid, None)
            self._reroute_classic(spec, actor=inf.actor is not None,
                                  inf=inf)
            return
        self._release_pins(inf)
        self._cancelled.discard(spec.task_id)
        results = msg.get("results") or []
        got = set()
        for res in results:
            got.add(res.object_id)
            if res.inline is not None:
                contained = getattr(res, "contained", None)
                if contained:
                    self._take_contained_pins(spec, res, contained)
                self.owned.fulfill_inline(res.object_id, res.inline[0],
                                          res.inline[1])
                if self.owned.take_promote(res.object_id):
                    # A classic-path consumer is waiting on the head for
                    # these bytes (see _promote_owned_args).
                    self.core.promote_owned_to_head(res.object_id)
            else:
                # Large result: sealed into the node store; head directory
                # learned it from the worker's seal message.
                self._make_extern_mirrored(res.object_id)
        if error is not None:
            for oid in spec.return_ids():
                if oid not in got:
                    self.owned.fulfill_error(oid, error[0], error[1])
                    if self.owned.take_promote(oid):
                        self.core.promote_owned_to_head(oid)

    def _is_self(self, owner: Optional[dict]) -> bool:
        mine = self.core.direct_addr
        if owner is None or mine is None:
            return False
        if owner is mine:
            return True
        return owner.get("unix") is not None \
            and owner.get("unix") == mine.get("unix")

    def _take_contained_pins(self, spec: TaskSpec, res, contained):
        """Contained-ref handover: register `res:` pins (tied to the
        result entry's lifetime) at each nested ref's owner, then release
        the returner's `ret:` pin — ordered on the same channel so the
        object can never be unpinned-before-pinned."""
        token = b"res:" + res.object_id.binary()
        ret_tok = b"ret:" + spec.task_id.binary()
        for oid_b, owner, prepinned in contained:
            oid = ObjectID(oid_b)
            try:
                if owner is None:
                    # Head-counted nested ref: swap the returner's ret:
                    # head ref for a res: ref tied to the result entry.
                    # Both ride OUR head conn in order, so the add lands
                    # before the release.
                    self.core.transport.request_oneway(
                        "add_ref", {"oid": oid, "holder": token})
                    self.core.transport.request_oneway(
                        "remove_ref", {"oid": oid, "holder": ret_tok})
                elif self._is_self(owner):
                    self.owned.pin(oid, token)
                    if prepinned:
                        self.owned.unpin(oid, ret_tok)
                else:
                    ch = self._fetch_chan_for(owner)
                    if ch is not None:
                        ch.pin(oid, token)
                        if prepinned:
                            ch.unpin(oid, ret_tok)
            except Exception:
                pass
        if not self.owned.set_linked(res.object_id, (token, contained)):
            # Result entry already gone (nobody holds it): release now.
            self.owned.released_links.append((token, contained))

    def _drain_released_links(self):
        while True:
            try:
                token, contained = self.owned.released_links.popleft()
            except IndexError:
                return
            for oid_b, owner, _prepinned in contained:
                oid = ObjectID(oid_b)
                try:
                    if owner is None:
                        self.core.transport.request_oneway(
                            "remove_ref", {"oid": oid, "holder": token})
                    elif self._is_self(owner):
                        self.owned.unpin(oid, token)
                    else:
                        self.unpin_at_owner(oid, owner, token)
                except Exception:
                    pass

    def _on_chan_close(self, chan: DirectChannel):
        """A direct connection died.  Leased tasks retry (budget permitting)
        via the classic path; actor tasks re-resolve the actor and replay in
        order (the reference's restart replay, task_manager.h)."""
        dead_actor: Optional[_ActorClient] = None
        to_retry: List[_Inflight] = []
        to_fail: List[_Inflight] = []
        with self._lock:
            for key, pool in list(self._leases.items()):
                for lease in list(pool):
                    if lease.chan is chan:
                        lease.alive = False
                        pool.remove(lease)
            dead_worker_id: Optional[bytes] = None
            for ac in self._actors.values():
                if ac.chan is chan:
                    dead_actor = ac
                    ac.chan = None
                    dead_worker_id = ac.worker_id
            if dead_actor is not None:
                replay: List[TaskSpec] = []
                no_budget: List[TaskSpec] = []
                for tid, spec in list(dead_actor.inflight.items()):
                    inf = self._inflight.get(tid)
                    if spec.task_id in self._cancelled:
                        self._inflight.pop(tid, None)
                        if inf is not None:
                            to_fail.append(inf)
                    elif spec.attempt < spec.max_retries:
                        spec.attempt += 1
                        replay.append(spec)
                    else:
                        # No retry budget: let the HEAD fail it — the head
                        # processes the worker death and produces the
                        # authoritative cause/ordering (our local verdict
                        # would race calls submitted before the head
                        # notices the death).
                        no_budget.append(spec)
                dead_actor.inflight.clear()
                dead_actor.queue.extendleft(reversed(replay))
                dead_actor.state = A_RESOLVING
            for tid, inf in list(self._inflight.items()):
                if inf.lease is not None and inf.lease.chan is chan:
                    self._inflight.pop(tid, None)
                    if inf.spec.attempt < inf.spec.max_retries \
                            and inf.spec.task_id not in self._cancelled:
                        inf.spec.attempt += 1
                        to_retry.append(inf)
                    else:
                        to_fail.append(inf)
        for inf in to_fail:
            self._release_pins(inf)
            cancelled = inf.spec.task_id in self._cancelled
            self._cancelled.discard(inf.spec.task_id)
            err = (exc.RayTpuError("task cancelled") if cancelled
                   else (exc.ActorDiedError("actor worker died")
                         if inf.actor is not None
                         else exc.WorkerCrashedError(
                             "worker died executing a direct task")))
            meta, data = _pack_error(err)
            for oid in inf.spec.return_ids():
                self.owned.fulfill_error(oid, meta, data)
        for inf in to_retry:
            self._release_pins(inf)
            if not self.submit_task(inf.spec):
                self._reroute_classic(inf.spec)
        if dead_actor is not None:
            for spec in no_budget:
                self._reroute_classic(spec, actor=True,
                                      dead_worker=dead_worker_id)
            if not self._closed:
                self._resolve_actor_async(dead_actor)

    # ================= cancel =================
    def cancel(self, task_id: TaskID) -> bool:
        """True if this submitter knows the task (direct in-flight)."""
        with self._lock:
            inf = self._inflight.get(task_id.binary())
            if inf is None:
                return False
            self._cancelled.add(task_id)
            chan = (inf.lease.chan if inf.lease is not None
                    else inf.actor.chan if inf.actor is not None else None)
            wid = inf.lease.worker_id if inf.lease is not None else None
        if chan is not None:
            chan.cancel(task_id)  # drops it if still queued worker-side
        if wid is not None:
            # Running normal task: match the classic coarse-cancel (kill the
            # worker; the channel-close path fails the task as cancelled).
            try:
                self.core.transport.request_oneway("kill_worker",
                                                   {"worker_id": wid})
            except Exception:
                pass
        return True

    # ================= pins / borrows =================
    def _commit(self, spec: TaskSpec) -> list:
        """Create owned entries for returns; pin ref args for the task's
        lifetime (owner-side arg pinning — the reference pins at the head
        via dependency_manager.h; here the *owner* of each arg pins)."""
        for oid in spec.return_ids():
            self.owned.create_pending(oid)
        if not spec.args and not spec.kwargs:
            return None
        token = b"task:" + spec.task_id.binary()
        pins = []
        for arg in list(spec.args) + list(spec.kwargs.values()):
            oids = ([arg.ref] if arg.ref is not None else []) + arg.contained
            owners = dict(getattr(arg, "contained_owners", None) or {})
            if arg.ref is not None and getattr(arg, "owner", None):
                owners[arg.ref.binary()] = arg.owner
            for oid in oids:
                if self.owned.contains(oid):
                    self.owned.pin(oid, token)
                    pins.append(("owned", oid, None))
                    continue
                owner = owners.get(oid.binary())
                if owner:
                    ch = self._fetch_chan_for(owner)
                    if ch is not None:
                        ch.pin(oid, token)
                        pins.append(("owner", oid, owner))
                        continue
                self.core.transport.request_oneway(
                    "add_ref", {"oid": oid, "holder": token})
                pins.append(("head", oid, None))
        return pins

    def _release_pins(self, inf: _Inflight):
        token = b"task:" + inf.spec.task_id.binary()
        for kind, oid, extra in inf.pins:
            try:
                if kind == "owned":
                    self.owned.unpin(oid, token)
                elif kind == "owner":
                    ch = self._fetch_chan_for(extra)
                    if ch is not None:
                        ch.unpin(oid, token)
                else:
                    self.core.transport.request_oneway(
                        "remove_ref", {"oid": oid, "holder": token})
            except Exception:
                pass
        inf.pins = []

    def _fetch_chan_for(self, addr: Optional[dict]) -> Optional[DirectChannel]:
        ep = pick_endpoint(addr, self.host_key)
        if ep is None:
            return None
        key = (ep[0], tuple(ep[1]) if isinstance(ep[1], (list, tuple))
               else ep[1])
        with self._lock:
            ch = self._fetch_chans.get(key)
            if ch is not None and ch.alive:
                return ch
            try:
                ch = DirectChannel(ep, self.authkey, on_done=self._on_done,
                                   on_close=self._on_chan_close)
            except Exception:
                return None
            self._fetch_chans[key] = ch
            return ch

    def fetch_from_owner(self, oid: ObjectID, owner: dict,
                         timeout: Optional[float],
                         nowait: bool = False) -> Optional[dict]:
        """Fetch an object's bytes from its owner.  Returns the fetch_r
        message, or None if the owner is unreachable."""
        ch = self._fetch_chan_for(owner)
        if ch is None:
            return None
        try:
            return ch.fetch(oid, timeout, nowait=nowait)
        except FuturesTimeoutError:
            raise exc.GetTimeoutError(f"get({oid}) timed out")
        except Exception:
            return None

    def pin_at_owner(self, oid: ObjectID, owner: dict, token: bytes) -> bool:
        ch = self._fetch_chan_for(owner)
        return ch is not None and ch.pin(oid, token)

    def unpin_at_owner(self, oid: ObjectID, owner: dict, token: bytes):
        ch = self._fetch_chan_for(owner)
        if ch is not None:
            ch.unpin(oid, token)

    # ================= maintenance =================
    def _maintenance(self):
        while not self._closed:
            time.sleep(0.2)
            self._drain_released_links()
            drop: List[Tuple[tuple, _Lease]] = []
            now = time.monotonic()
            with self._lock:
                for key, pool in self._leases.items():
                    for lease in list(pool):
                        if not lease.alive or (
                                lease.inflight == 0
                                and now - lease.idle_since
                                > self._lease_idle_s):
                            pool.remove(lease)
                            drop.append((key, lease))
            for _key, lease in drop:
                lease.alive = False
                try:
                    lease.chan.close()
                except Exception:
                    pass
                try:
                    self.core.transport.request_oneway(
                        "return_lease", {"worker_id": lease.worker_id})
                except Exception:
                    pass

    def shutdown(self):
        with self._lock:
            self._closed = True
            leases = [l for pool in self._leases.values() for l in pool]
            self._leases.clear()
            chans = list(self._fetch_chans.values())
            self._fetch_chans.clear()
            actors = list(self._actors.values())
            self._actors.clear()
        for lease in leases:
            try:
                lease.chan.close()
            except Exception:
                pass
            try:
                self.core.transport.request_oneway(
                    "return_lease", {"worker_id": lease.worker_id})
            except Exception:
                pass
        for ac in actors:
            if ac.chan is not None:
                try:
                    ac.chan.close()
                except Exception:
                    pass
        for ch in chans:
            try:
                ch.close()
            except Exception:
                pass


def _pack_error(error: BaseException) -> Tuple[bytes, bytes]:
    return ser.pack(ser.serialize(error))
