"""Local mode: every task and actor call executes inline in the driver.

Reference: ray.init(local_mode=True) (python/ray/_private/worker.py —
the LocalModeManager executing task specs synchronously).  The debugging
contract: no subprocesses, no serialization, plain stack traces straight
into user code, pdb works.  Exceptions raised by tasks propagate to
``get`` as the ORIGINAL exception (not a wrapped TaskError) — the point
of local mode is an undisturbed debugger.

Scope: tasks, actors (incl. named), put/get/wait, nested calls.  Cluster
features that require real processes (placement groups as constraints,
TPU partitioning, spilling) are accepted and ignored, matching the
reference's local-mode behavior.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, WorkerID
from ray_tpu.object_ref import ObjectRef


class _Stored:
    __slots__ = ("value", "error")

    def __init__(self, value=None, error: Optional[BaseException] = None):
        self.value = value
        self.error = error


class LocalModeTransport:
    """Answers the head-request ops the public API issues, locally: real
    answers where one exists (resources, named actors, KV, state), benign
    accept-and-ignore for cluster-only machinery (placement groups,
    cancel) — so any script using those APIs still debugs in local mode."""

    def __init__(self, worker: "LocalModeWorker"):
        self._w = worker
        self._kv: Dict[tuple, bytes] = {}

    def request(self, op: str, payload: dict,
                timeout: Optional[float] = None):
        import os as _os

        w = self._w
        if op == "cluster_resources":
            return {"CPU": float(_os.cpu_count() or 1),
                    "memory": 2.0 * 1024 ** 3}
        if op == "state":
            what = payload.get("what")
            if what == "actors":
                with w._lock:
                    return [{"actor_id": aid.hex(), "state": "ALIVE",
                             "name": None}
                            for aid in w._actors]
            return []
        if op == "kill_actor":
            w.kill_actor(payload["actor_id"])
            return True
        if op == "get_actor":
            return w.get_named_actor_info(payload["name"])
        if op == "kv":
            action = payload.get("action")
            key = (payload.get("ns", "default"), payload.get("key"))
            if action == "put":
                self._kv[key] = payload.get("value")
                return True
            if action == "get":
                return self._kv.get(key)
            if action == "del":
                return self._kv.pop(key, None) is not None
            if action == "keys":
                ns = payload.get("ns", "default")
                return [k for n, k in self._kv if n == ns]
        if op == "pg_ready":
            return True
        # Everything else (create_pg, remove_pg, cancel, add_ref, ...):
        # accepted and ignored — there is no cluster to configure.
        return None

    def request_oneway(self, op: str, payload: dict):
        self.request(op, payload)

    def notify(self, msg: dict):
        pass

    def close(self):
        pass


class LocalModeWorker:
    """The CoreWorker surface the public API uses, executed inline."""

    def __init__(self):
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self._store: Dict[ObjectID, _Stored] = {}
        self._actors: Dict[ActorID, Any] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._lock = threading.RLock()
        self.mode = "local"
        self.transport = LocalModeTransport(self)

    # ---- object plane ----
    def put(self, value) -> ObjectRef:
        oid = ObjectID.from_random()
        with self._lock:
            self._store[oid] = _Stored(value=value)
        return ObjectRef(oid)

    def store_result(self, value=None,
                     error: Optional[BaseException] = None) -> ObjectRef:
        oid = ObjectID.from_random()
        with self._lock:
            self._store[oid] = _Stored(value=value, error=error)
        return ObjectRef(oid)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        out = []
        for r in ([refs] if single else list(refs)):
            with self._lock:
                stored = self._store.get(r.id)
            if stored is None:
                raise KeyError(f"unknown object {r.id} (local mode)")
            if stored.error is not None:
                raise stored.error
            out.append(stored.value)
        return out[0] if single else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        refs = list(refs)
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        # Everything is already computed inline.
        return refs[:num_returns], refs[num_returns:]

    # ---- execution ----
    def run_function(self, fn, args, kwargs, num_returns: int = 1):
        args = [self._resolve(a) for a in args]
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        try:
            out = fn(*args, **kwargs)
            if num_returns not in (0, 1):
                # Same contract as cluster mode: the return count must
                # match the declaration — surfacing the mismatch at get()
                # keeps local-mode-tested code deployable.
                out = list(out)
                if len(out) != num_returns:
                    raise ValueError(
                        f"task declared num_returns={num_returns} but "
                        f"returned {len(out)} values")
        except BaseException as e:  # noqa: BLE001 — stored, raised at get
            if num_returns == 1:
                return self.store_result(error=e)
            return [self.store_result(error=e) for _ in range(num_returns)]
        if num_returns == 0:
            return None
        if num_returns == 1:
            return self.store_result(out)
        return [self.store_result(v) for v in out]

    def create_actor(self, cls, args, kwargs, name: Optional[str] = None):
        args = [self._resolve(a) for a in args]
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        instance = cls(*args, **kwargs)
        actor_id = ActorID.from_random()
        with self._lock:
            self._actors[actor_id] = instance
            if name:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
        return actor_id

    def call_actor(self, actor_id: ActorID, method: str, args, kwargs,
                   num_returns: int = 1):
        with self._lock:
            instance = self._actors.get(actor_id)
        if instance is None:
            from ray_tpu import exceptions as exc

            return self.store_result(
                error=exc.ActorDiedError("actor killed (local mode)"))
        return self.run_function(getattr(instance, method), args, kwargs,
                                 num_returns)

    def kill_actor(self, actor_id: ActorID):
        with self._lock:
            self._actors.pop(actor_id, None)
            for name, aid in list(self._named_actors.items()):
                if aid == actor_id:
                    del self._named_actors[name]

    def get_named_actor(self, name: str) -> ActorID:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r} (local mode)")
        return aid

    def get_named_actor_info(self, name: str) -> dict:
        """get_actor() payload matching the head's shape (actor id + a
        creation-spec shim carrying method names from the CLASS, like the
        cluster creation path)."""
        from types import SimpleNamespace

        with self._lock:
            aid = self._named_actors.get(name)
            if aid is None:
                raise ValueError(f"no actor named {name!r} (local mode)")
            inst = self._actors[aid]
        cls = type(inst)
        methods = [n for n in dir(cls)
                   if callable(getattr(cls, n, None))
                   and not n.startswith("__")]
        return {"actor_id": aid,
                "creation_spec": SimpleNamespace(
                    actor_method_names=methods,
                    name=f"{cls.__name__}.__init__")}

    def _resolve(self, v):
        if isinstance(v, ObjectRef):
            return self.get(v)
        return v

    # ---- misc surface ----
    def add_local_ref(self, oid: ObjectID, owner_addr=None):
        """ObjectRef lifetime hooks: local mode keeps values until
        shutdown (debugging runs are short; matches the reference's
        local-mode no-GC behavior)."""

    def remove_local_ref(self, oid: ObjectID, owner_addr=None):
        pass

    def shutdown(self):
        with self._lock:
            self._store.clear()
            self._actors.clear()
            self._named_actors.clear()
