"""Chaos / fault-injection hooks for schedule-perturbation testing.

Reference: asio delay injection (src/ray/common/asio/asio_chaos.h:22, flag
RAY_testing_asio_delay_us in ray_config_def.h:735-738) and the node-killer
actor (python/ray/_private/test_utils.py:1337).

Enable delays with RAY_TPU_TESTING_DELAY_MS="<op_substr>:<min>:<max>", e.g.
"submit:0:20" delays every task submission by 0-20ms.  `kill_random_worker`
is the in-process node-killer equivalent.
"""
from __future__ import annotations

import os
import random
import time
from typing import Optional, Tuple


def _parse() -> Optional[Tuple[str, float, float]]:
    spec = os.environ.get("RAY_TPU_TESTING_DELAY_MS")
    if not spec:
        return None
    try:
        op, lo, hi = spec.split(":")
        return op, float(lo), float(hi)
    except ValueError:
        return None


def maybe_delay(op: str):
    """Called on head-side operations; sleeps if the op matches the spec."""
    parsed = _parse()
    if parsed is None:
        return
    needle, lo, hi = parsed
    if needle in op:
        time.sleep(random.uniform(lo, hi) / 1000.0)


def kill_random_worker(head=None, rng: Optional[random.Random] = None) -> bool:
    """Kill one random busy worker process (crash injection). Returns True
    if something was killed."""
    import ray_tpu

    head = head or ray_tpu._global_head()
    rng = rng or random.Random()
    with head._lock:
        candidates = [
            w for r in head.raylets.values() for w in r.workers.values()
            if w.proc is not None and w.conn is not None
        ]
    if not candidates:
        return False
    victim = rng.choice(candidates)
    try:
        victim.proc.kill()
        return True
    except Exception:
        return False
