"""Chaos / fault-injection hooks for schedule-perturbation testing.

Reference: asio delay injection (src/ray/common/asio/asio_chaos.h:22, flag
RAY_testing_asio_delay_us in ray_config_def.h:735-738) and the node-killer
actor (python/ray/_private/test_utils.py:1337).

Enable delays with RAY_TPU_TESTING_DELAY_MS="<op_substr>:<min>:<max>", e.g.
"submit:0:20" delays every task submission by 0-20ms.  `kill_random_worker`
is the in-process node-killer equivalent.

Gang-level fault injection (mesh fault-tolerance testing): set
RAY_TPU_TESTING_KILL_SCHEDULE to a ``;``-separated list of
``<op>:<rank>:<nth>[:<generation>]`` entries — when the matching op fires
for the ``nth`` time (1-based, counted per process) at ``rank`` in gang
``generation`` the process SIGKILLs itself, simulating a hard rank crash
mid-collective.  ``rank`` and ``generation`` accept ``*`` (any); generation
defaults to ``0`` so a restarted gang (which re-exports
RTPU_MESH_GENERATION) survives by default, making restart-then-succeed
loops deterministic.  Kill sites: ``mesh_run`` (MeshWorker.run entry) and
``train_report`` (TrainWorker result reporting).  Driver-side,
``kill_mesh_rank`` murders a specific (or seeded-random) rank of a live
MeshGroup/WorkerGroup by killing its hosting worker process.
"""
from __future__ import annotations

import os
import random
import time
from typing import List, Optional, Tuple

KILL_SCHEDULE_ENV = "RAY_TPU_TESTING_KILL_SCHEDULE"
GENERATION_ENV = "RTPU_MESH_GENERATION"


def _parse() -> Optional[Tuple[str, float, float]]:
    spec = os.environ.get("RAY_TPU_TESTING_DELAY_MS")
    if not spec:
        return None
    try:
        op, lo, hi = spec.split(":")
        return op, float(lo), float(hi)
    except ValueError:
        return None


def maybe_delay(op: str):
    """Called on head-side operations; sleeps if the op matches the spec."""
    parsed = _parse()
    if parsed is None:
        return
    needle, lo, hi = parsed
    if needle in op:
        time.sleep(random.uniform(lo, hi) / 1000.0)


class ChaosSchedule:
    """A deterministic rank-kill schedule, parsed once per process.

    Entries are (op, rank, nth, generation); rank/generation may be None
    (wildcard).  ``should_die(op, rank)`` is called at each kill site with
    the process's per-op invocation count and the gang generation from
    RTPU_MESH_GENERATION."""

    def __init__(self, entries: List[Tuple[str, Optional[int], int,
                                           Optional[int]]]):
        self.entries = list(entries)
        self._counts: dict = {}

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosSchedule":
        entries = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (3, 4):
                continue
            op = bits[0]
            rank = None if bits[1] == "*" else int(bits[1])
            nth = int(bits[2])
            gen: Optional[int] = 0
            if len(bits) == 4:
                gen = None if bits[3] == "*" else int(bits[3])
            entries.append((op, rank, nth, gen))
        return cls(entries)

    @classmethod
    def from_env(cls) -> Optional["ChaosSchedule"]:
        spec = os.environ.get(KILL_SCHEDULE_ENV)
        return cls.from_spec(spec) if spec else None

    def should_die(self, op: str, rank: Optional[int]) -> bool:
        if not self.entries:
            return False
        count = self._counts.get(op, 0) + 1
        self._counts[op] = count
        try:
            generation = int(os.environ.get(GENERATION_ENV, "0"))
        except ValueError:
            generation = 0
        for e_op, e_rank, e_nth, e_gen in self.entries:
            if e_op != op:
                continue
            if e_rank is not None and e_rank != rank:
                continue
            if e_gen is not None and e_gen != generation:
                continue
            if count == e_nth:
                return True
        return False


_schedule: Optional[ChaosSchedule] = None
_schedule_spec: Optional[str] = None


def maybe_die(op: str, rank: Optional[int] = None) -> None:
    """Worker-side kill site: consult the env schedule and SIGKILL the
    current process on a match (a hard crash — no atexit, no cleanup —
    exactly what a preempted TPU host looks like to the gang)."""
    global _schedule, _schedule_spec
    spec = os.environ.get(KILL_SCHEDULE_ENV)
    if not spec:
        return
    if _schedule is None or spec != _schedule_spec:
        _schedule = ChaosSchedule.from_spec(spec)
        _schedule_spec = spec
    if _schedule.should_die(op, rank):
        import signal

        os.kill(os.getpid(), signal.SIGKILL)


def _kill_actor_process(actor, head=None) -> bool:
    """Kill the worker PROCESS hosting `actor` (crash injection, not the
    graceful ray_tpu.kill path).  Returns True if a process was killed."""
    import ray_tpu

    head = head or ray_tpu._global_head()
    if head is None:
        return False
    with head._lock:
        info = head.gcs.get_actor_info(actor._actor_id)
        wid = info.worker_id if info is not None else None
        handle = None
        if wid is not None:
            _, handle = head._find_worker(wid)
    if handle is None or handle.proc is None:
        return False
    try:
        handle.proc.kill()
        return True
    except Exception:
        return False


def kill_mesh_rank(group, rank: Optional[int] = None,
                   rng: Optional[random.Random] = None,
                   head=None) -> Optional[int]:
    """Kill one rank of a MeshGroup / Train WorkerGroup by murdering its
    hosting worker process.  `rank=None` picks one with the seeded `rng`
    (deterministic chaos).  Returns the killed rank, or None if nothing
    could be killed."""
    workers = getattr(group, "workers", group)
    if not workers:
        return None
    if rank is None:
        rng = rng or random.Random()
        rank = rng.randrange(len(workers))
    return rank if _kill_actor_process(workers[rank], head=head) else None


def kill_random_worker(head=None, rng: Optional[random.Random] = None) -> bool:
    """Kill one random busy worker process (crash injection). Returns True
    if something was killed."""
    import ray_tpu

    head = head or ray_tpu._global_head()
    rng = rng or random.Random()
    with head._lock:
        candidates = [
            w for r in head.raylets.values() for w in r.workers.values()
            if w.proc is not None and w.conn is not None
        ]
    if not candidates:
        return False
    victim = rng.choice(candidates)
    try:
        victim.proc.kill()
        return True
    except Exception:
        return False
