"""Chaos / fault-injection hooks for schedule-perturbation testing.

Reference: asio delay injection (src/ray/common/asio/asio_chaos.h:22, flag
RAY_testing_asio_delay_us in ray_config_def.h:735-738) and the node-killer
actor (python/ray/_private/test_utils.py:1337).

Enable delays with RAY_TPU_TESTING_DELAY_MS="<op_substr>:<min>:<max>", e.g.
"submit:0:20" delays every task submission by 0-20ms.  `kill_random_worker`
is the in-process node-killer equivalent.

Gang-level fault injection (mesh fault-tolerance testing): set
RAY_TPU_TESTING_KILL_SCHEDULE to a ``;``-separated list of
``<op>:<rank>:<nth>[:<generation>]`` entries — when the matching op fires
for the ``nth`` time (1-based, counted per process) at ``rank`` in gang
``generation`` the process SIGKILLs itself, simulating a hard rank crash
mid-collective.  ``rank`` and ``generation`` accept ``*`` (any); generation
defaults to ``0`` so a restarted gang (which re-exports
RTPU_MESH_GENERATION) survives by default, making restart-then-succeed
loops deterministic.  Kill sites: ``mesh_run`` (MeshWorker.run entry),
``train_report`` (TrainWorker result reporting), and the node-agent
sites ``node_agent_spawn`` (counted per spawn_worker command),
``node_agent_msg`` (per handled head message) and ``node_agent_tick``
(per 0.5s reap tick) — a node-agent match SIGKILLs the agent AND all of
its worker children, simulating whole-node loss.  Driver-side,
``kill_mesh_rank`` murders a specific (or seeded-random) rank of a live
MeshGroup/WorkerGroup by killing its hosting worker process, and
``kill_node`` SIGKILLs a node-agent subprocess with its whole process
group.

Message-level transport faults (drop/duplicate/delay/sever individual
control- and data-plane messages, deterministic and seeded): set
RAY_TPU_TESTING_NET_SCHEDULE — see :class:`NetSchedule` and
docs/FAULT_TOLERANCE.md "RPC deadlines, retries, and transport chaos".
"""
from __future__ import annotations

import os
import random
import time
from typing import List, Optional, Tuple

KILL_SCHEDULE_ENV = "RAY_TPU_TESTING_KILL_SCHEDULE"
GENERATION_ENV = "RTPU_MESH_GENERATION"
NET_SCHEDULE_ENV = "RAY_TPU_TESTING_NET_SCHEDULE"


# ---------------------------------------------------------------------------
# Message-level transport faults
# ---------------------------------------------------------------------------
class NetSchedule:
    """A seeded, deterministic message-fault schedule.

    RAY_TPU_TESTING_NET_SCHEDULE is a ``;``-separated list of
    ``<op>:<kind>:<prob>:<seed>[:<times>[:<delay_ms>]]`` entries:

    - ``op``    — substring matched against the fault-point label.
      Labels are directional: ``request:<op>`` / ``notify:<type>`` on the
      send side, ``reply:<op>`` / ``push:<type>`` on the receive side,
      and ``pull`` on the transfer.py data channel.
    - ``kind``  — ``drop`` (message vanishes), ``dup`` (delivered twice),
      ``delay`` (sleeps ``delay_ms``, default 25), ``sever`` (the
      connection is closed mid-flight, like a mid-stream RST).
    - ``prob``  — per-message trigger probability, drawn from a dedicated
      ``random.Random(seed)`` so a schedule replays identically.
    - ``times`` — optional cap on total triggers (e.g. ``1`` = exactly
      the first matching draw fires, then the link heals).

    Example: ``reply:resolve:drop:0.3:42;request:submit:dup:1.0:7:1``
    drops ~30% of resolve replies forever and duplicates exactly one
    submit frame.
    """

    def __init__(self, entries):
        import threading

        # entries: list of dicts {needle, kind, prob, rng, left, delay_ms}
        self.entries = entries
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str) -> "NetSchedule":
        entries = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) < 4:
                continue
            # The op label may itself contain ':' ("request:submit"), so
            # anchor the parse on the first known fault kind.
            try:
                kinds = ("drop", "dup", "delay", "sever")
                ki = next(i for i in range(1, len(bits))
                          if bits[i] in kinds)
                op = ":".join(bits[:ki])
                kind = bits[ki]
                prob = float(bits[ki + 1])
                seed = int(bits[ki + 2])
                times = (int(bits[ki + 3])
                         if len(bits) > ki + 3 and bits[ki + 3] else None)
                delay_ms = (float(bits[ki + 4])
                            if len(bits) > ki + 4 else 25.0)
            except (StopIteration, ValueError, IndexError):
                continue
            entries.append({"needle": op, "kind": kind, "prob": prob,
                            "rng": random.Random(seed),
                            "left": times, "delay_ms": delay_ms})
        return cls(entries)

    def fault(self, label: str) -> Optional[Tuple[str, float]]:
        """Consult the schedule for a message at ``label``; returns
        ``(kind, delay_ms)`` when a fault fires, else None.  First
        matching entry wins; draws are per-entry deterministic."""
        for e in self.entries:
            if e["needle"] not in label:
                continue
            if e["left"] is not None and e["left"] <= 0:
                continue
            if e["rng"].random() >= e["prob"]:
                continue
            if e["left"] is not None:
                with self._lock:
                    if e["left"] <= 0:
                        continue
                    e["left"] -= 1
            from ray_tpu._private import retry as _retry

            _retry.note("net_faults")
            return e["kind"], e["delay_ms"]
        return None


def net_request_label(op: str, payload: Optional[dict]) -> str:
    """Fault-point label for a request frame.  Acked notifies (op
    ``notify_msg``) append the inner message type so schedules can target
    the real op ("seal", "task_done") instead of the envelope."""
    if op == "notify_msg" and isinstance(payload, dict):
        inner = payload.get("msg")
        if isinstance(inner, dict) and inner.get("type"):
            return f"notify_msg:{inner['type']}"
    return op


_net_schedule: Optional[NetSchedule] = None
_net_schedule_spec: Optional[str] = None


def net_schedule() -> Optional[NetSchedule]:
    """Process-wide schedule parsed from RAY_TPU_TESTING_NET_SCHEDULE
    (re-parsed when the env var changes, like the kill schedule)."""
    global _net_schedule, _net_schedule_spec
    spec = os.environ.get(NET_SCHEDULE_ENV)
    if not spec:
        if _net_schedule is not None:
            _net_schedule = None
            _net_schedule_spec = None
        return None
    if _net_schedule is None or spec != _net_schedule_spec:
        _net_schedule = NetSchedule.from_spec(spec)
        _net_schedule_spec = spec
    return _net_schedule


def net_fault(label: str) -> Optional[Tuple[str, float]]:
    sched = net_schedule()
    return sched.fault(label) if sched is not None else None


class FaultableConn:
    """Fault-injecting wrapper around a multiprocessing Connection.

    Installed under ConnTransport (and the node agent's head link) when a
    net schedule is active.  Send-side labels come from the outgoing
    frame (``request:<op>`` / ``notify:<type>``); receive-side labels
    from the incoming frame (``reply:<op>`` / ``push:<type>``, the op
    echoed in reply frames by the head).  ``sever`` closes the underlying
    connection — exactly what a dropped TCP link looks like to both
    reader loops, driving the reconnect/resend path.
    """

    def __init__(self, conn, schedule_fn=net_fault):
        self._conn = conn
        self._fault = schedule_fn
        self._recv_dups = []

    # -- label derivation --
    @staticmethod
    def _send_label(msg) -> str:
        if isinstance(msg, dict):
            t = msg.get("type")
            if t == "request":
                return f"request:{net_request_label(msg.get('op', ''), msg.get('payload'))}"
            if t == "notify":
                return f"notify:{msg.get('op', '')}"
            return f"notify:{t}"
        return "notify:raw"

    @staticmethod
    def _recv_label(msg) -> str:
        if isinstance(msg, dict):
            t = msg.get("type")
            if t == "reply":
                return f"reply:{msg.get('op', '')}"
            return f"push:{t}"
        return "push:raw"

    # -- faulted endpoints --
    def send(self, msg):
        act = self._fault(self._send_label(msg))
        if act is None:
            return self._conn.send(msg)
        kind, delay_ms = act
        if kind == "drop":
            return None
        if kind == "dup":
            self._conn.send(msg)
            return self._conn.send(msg)
        if kind == "delay":
            time.sleep(delay_ms / 1000.0)
            return self._conn.send(msg)
        if kind == "sever":
            try:
                self._conn.close()
            finally:
                raise OSError("chaos: connection severed (send)")
        return self._conn.send(msg)

    def recv(self):
        while True:
            if self._recv_dups:
                return self._recv_dups.pop()
            msg = self._conn.recv()
            act = self._fault(self._recv_label(msg))
            if act is None:
                return msg
            kind, delay_ms = act
            if kind == "drop":
                continue
            if kind == "dup":
                self._recv_dups.append(msg)
                return msg
            if kind == "delay":
                time.sleep(delay_ms / 1000.0)
                return msg
            if kind == "sever":
                try:
                    self._conn.close()
                finally:
                    raise EOFError("chaos: connection severed (recv)")
            return msg

    # -- transparent delegation --
    def __getattr__(self, name):
        return getattr(self._conn, name)


def wrap_net_faults(conn):
    """Wrap ``conn`` in a FaultableConn when a net schedule is active
    (identity no-op otherwise, and never double-wraps)."""
    if isinstance(conn, FaultableConn):
        return conn
    return FaultableConn(conn) if net_schedule() is not None else conn


def _parse() -> Optional[Tuple[str, float, float]]:
    spec = os.environ.get("RAY_TPU_TESTING_DELAY_MS")
    if not spec:
        return None
    try:
        op, lo, hi = spec.split(":")
        return op, float(lo), float(hi)
    except ValueError:
        return None


def maybe_delay(op: str):
    """Called on head-side operations; sleeps if the op matches the spec."""
    parsed = _parse()
    if parsed is None:
        return
    needle, lo, hi = parsed
    if needle in op:
        time.sleep(random.uniform(lo, hi) / 1000.0)


class ChaosSchedule:
    """A deterministic rank-kill schedule, parsed once per process.

    Entries are (op, rank, nth, generation); rank/generation may be None
    (wildcard).  ``should_die(op, rank)`` is called at each kill site with
    the process's per-op invocation count and the gang generation from
    RTPU_MESH_GENERATION."""

    def __init__(self, entries: List[Tuple[str, Optional[int], int,
                                           Optional[int]]]):
        self.entries = list(entries)
        self._counts: dict = {}

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosSchedule":
        entries = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (3, 4):
                continue
            op = bits[0]
            rank = None if bits[1] == "*" else int(bits[1])
            nth = int(bits[2])
            gen: Optional[int] = 0
            if len(bits) == 4:
                gen = None if bits[3] == "*" else int(bits[3])
            entries.append((op, rank, nth, gen))
        return cls(entries)

    @classmethod
    def from_env(cls) -> Optional["ChaosSchedule"]:
        spec = os.environ.get(KILL_SCHEDULE_ENV)
        return cls.from_spec(spec) if spec else None

    def should_die(self, op: str, rank: Optional[int]) -> bool:
        if not self.entries:
            return False
        count = self._counts.get(op, 0) + 1
        self._counts[op] = count
        try:
            generation = int(os.environ.get(GENERATION_ENV, "0"))
        except ValueError:
            generation = 0
        for e_op, e_rank, e_nth, e_gen in self.entries:
            if e_op != op:
                continue
            if e_rank is not None and e_rank != rank:
                continue
            if e_gen is not None and e_gen != generation:
                continue
            if count == e_nth:
                return True
        return False


_schedule: Optional[ChaosSchedule] = None
_schedule_spec: Optional[str] = None


def check_die(op: str, rank: Optional[int] = None) -> bool:
    """Consult the env kill schedule for this kill site; True means the
    process is scheduled to die NOW (the caller decides how — plain
    SIGKILL for workers, children-then-self for node agents)."""
    global _schedule, _schedule_spec
    spec = os.environ.get(KILL_SCHEDULE_ENV)
    if not spec:
        return False
    if _schedule is None or spec != _schedule_spec:
        _schedule = ChaosSchedule.from_spec(spec)
        _schedule_spec = spec
    return _schedule.should_die(op, rank)


def maybe_die(op: str, rank: Optional[int] = None) -> None:
    """Worker-side kill site: consult the env schedule and SIGKILL the
    current process on a match (a hard crash — no atexit, no cleanup —
    exactly what a preempted TPU host looks like to the gang)."""
    if check_die(op, rank):
        import signal

        os.kill(os.getpid(), signal.SIGKILL)


def _kill_actor_process(actor, head=None) -> bool:
    """Kill the worker PROCESS hosting `actor` (crash injection, not the
    graceful ray_tpu.kill path).  Returns True if a process was killed."""
    import ray_tpu

    head = head or ray_tpu._global_head()
    if head is None:
        return False
    with head._lock:
        info = head.gcs.get_actor_info(actor._actor_id)
        wid = info.worker_id if info is not None else None
        handle = None
        if wid is not None:
            _, handle = head._find_worker(wid)
    if handle is None or handle.proc is None:
        return False
    try:
        handle.proc.kill()
        return True
    except Exception:
        return False


def kill_mesh_rank(group, rank: Optional[int] = None,
                   rng: Optional[random.Random] = None,
                   head=None) -> Optional[int]:
    """Kill one rank of a MeshGroup / Train WorkerGroup by murdering its
    hosting worker process.  `rank=None` picks one with the seeded `rng`
    (deterministic chaos).  Returns the killed rank, or None if nothing
    could be killed."""
    workers = getattr(group, "workers", group)
    if not workers:
        return None
    if rank is None:
        rng = rng or random.Random()
        rank = rng.randrange(len(workers))
    return rank if _kill_actor_process(workers[rank], head=head) else None


def kill_node(proc) -> bool:
    """SIGKILL an entire node: the agent subprocess AND every worker it
    spawned, atomically via its process group (start the agent with
    start_new_session=True — util.testing.start_node_agent does).  Falls
    back to killing just the agent when it shares our group.  This is the
    driver-side node-killer for chaos tests (reference:
    python/ray/_private/test_utils.py:1337 node killer)."""
    import signal

    pid = getattr(proc, "pid", proc)
    try:
        pgid = os.getpgid(pid)
    except OSError:
        return False
    try:
        if pgid != os.getpgid(0):
            os.killpg(pgid, signal.SIGKILL)
        else:
            os.kill(pid, signal.SIGKILL)
        return True
    except OSError:
        return False


def kill_random_worker(head=None, rng: Optional[random.Random] = None) -> bool:
    """Kill one random busy worker process (crash injection). Returns True
    if something was killed."""
    import ray_tpu

    head = head or ray_tpu._global_head()
    rng = rng or random.Random()
    with head._lock:
        candidates = [
            w for r in head.raylets.values() for w in r.workers.values()
            if w.proc is not None and w.conn is not None
        ]
    if not candidates:
        return False
    victim = rng.choice(candidates)
    try:
        victim.proc.kill()
        return True
    except Exception:
        return False
