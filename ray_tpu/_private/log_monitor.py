"""Worker log capture + driver echo.

Reference: worker processes write stdout/stderr to per-worker log files,
the LogMonitor tails them (python/ray/_private/log_monitor.py:104) and
publishes new lines through GCS pubsub, and the driver echoes them with a
worker prefix.  Same shape here: spawn_worker redirects output to
``<session_dir>/logs/worker-<id>.{out,err}``, a monitor thread in the head
tails every file and publishes ("LOG", record) on the GCS, and
``ray_tpu.init(log_to_driver=True)`` (the default) subscribes a printer.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, TextIO

POLL_S = 0.3


class LogMonitor:
    def __init__(self, logs_dir: str, gcs):
        self.logs_dir = logs_dir
        self.gcs = gcs
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="rtpu-log-monitor", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(POLL_S):
            try:
                self.poll_once()
            except Exception:
                pass

    def poll_once(self):
        if not os.path.isdir(self.logs_dir):
            return
        for name in os.listdir(self.logs_dir):
            path = os.path.join(self.logs_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(path, 0)
            if size <= off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            self._offsets[path] = size
            data = self._partial.pop(path, b"") + chunk
            *lines, tail = data.split(b"\n")
            if tail:
                self._partial[path] = tail
            if not lines:
                continue
            # worker-<hex>.out / worker-<hex>.err
            stem, _, stream = name.rpartition(".")
            source = stem.replace("worker-", "")
            for line in lines:
                self.gcs.publish("LOG", {
                    "source": source, "stream": stream,
                    "line": line.decode("utf-8", "replace")})

    def stop(self):
        self._stop.set()


def attach_driver_echo(gcs, out: Optional[TextIO] = None):
    """Print published worker log lines with a source prefix (the
    reference's driver log echo)."""
    out = out or sys.stderr

    def printer(record):
        prefix = f"({record['source'][:12]} {record['stream']})"
        try:
            print(f"{prefix} {record['line']}", file=out)
        except Exception:
            pass

    gcs.subscribe("LOG", printer)
    return printer
