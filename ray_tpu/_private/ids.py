"""Unique identifiers for jobs, tasks, actors, objects, nodes and placement groups.

TPU-native rethink of the reference's ID scheme (ref: src/ray/common/id.h,
python/ray/includes/unique_ids.pxi).  We keep the load-bearing design decision —
**ObjectIDs embed the ID of the task that created them plus a return-index**, so
ownership and lineage can be derived from the ID itself — but use a simpler
fixed-width random scheme rather than the reference's bit-packed flags.
"""
from __future__ import annotations

import os
import binascii
import random
import threading

_NIL = b"\x00"

# Process-local PRNG seeded from the OS once: ID generation is on the task
# submission hot path and os.urandom's syscall per ID costs ~100x a PRNG
# draw.  Uniqueness needs 128 random bits, not cryptographic strength.
# Re-seeded after fork so children don't replay the parent's stream.
_rng = random.Random(os.urandom(16))
_rng_pid = os.getpid()
_rng_lock = threading.Lock()


def _random_bytes(n: int) -> bytes:
    global _rng, _rng_pid
    with _rng_lock:
        if os.getpid() != _rng_pid:
            _rng = random.Random(os.urandom(16))
            _rng_pid = os.getpid()
        return _rng.getrandbits(n * 8).to_bytes(n, "little")


class BaseID:
    """A fixed-size binary identifier. Hashable, comparable, hex-printable."""

    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash((type(self).__name__, id_bytes))

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(binascii.unhexlify(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == _NIL * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """Actor id: 12 random bytes + 4-byte job id suffix."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def for_driver(cls, job_id: JobID):
        return cls(b"\xff" * (cls.SIZE - JobID.SIZE) + job_id.binary())


# Submission-hot-path task-id factory: a random 10-byte per-process prefix
# + 6-byte counter (GIL makes the counter draw atomic).  ~3x cheaper than
# from_random's locked PRNG draw; uniqueness holds because prefixes are
# process-unique and workers are spawned, never forked.
_task_id_prefix = os.urandom(10)
_task_id_prefix_pid = os.getpid()
_task_id_ctr = iter(range(1, 2**47))


def fast_task_id() -> TaskID:
    global _task_id_prefix, _task_id_prefix_pid, _task_id_ctr
    if os.getpid() != _task_id_prefix_pid:
        _task_id_prefix = os.urandom(10)
        _task_id_prefix_pid = os.getpid()
        _task_id_ctr = iter(range(1, 2**47))
    return TaskID(_task_id_prefix + next(_task_id_ctr).to_bytes(6, "little"))


class PlacementGroupID(BaseID):
    SIZE = 16


class ObjectID(BaseID):
    """Object id = task id (16B) + 4-byte big-endian return index.

    Index 0..2**31 are task returns; ``put`` objects use the high bit set,
    mirroring the reference's put-index space (src/ray/common/id.h).
    """

    SIZE = 20
    PUT_BIT = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        return cls(task_id.binary() + (cls.PUT_BIT | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[16:], "big") & ~self.PUT_BIT

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[16:], "big") & self.PUT_BIT)


# The reference calls these *Ref in the public API.
ObjectRefID = ObjectID
