"""Head: wires GCS + ClusterScheduler + Raylets + worker connections together.

This is the control-plane hub of a single-host (or virtual multi-node)
cluster: the reference's gcs_server + raylet processes collapsed into one
threaded component (see gcs.py for why).  Every mutation happens under one
lock; blocking waits (get/wait) are deferred-reply callbacks so connection
reader threads never block.

Responsibilities (reference equivalents in parentheses):
  - task manager: pending queue, retries, lineage reconstruction
    (src/ray/core_worker/task_manager.h:90, object_recovery_manager.h:41)
  - actor manager: creation leasing + restart FSM routing
    (src/ray/gcs/gcs_server/gcs_actor_manager.h:280)
  - object waits (src/ray/raylet/wait_manager.h)
  - worker connection routing (src/ray/rpc + direct transports)
"""
from __future__ import annotations

import os
import pickle
import tempfile
import threading
import time
import traceback
from collections import defaultdict, deque
from multiprocessing.connection import Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import serialization as ser
from ray_tpu._private.gcs import GCS, ActorState, NodeInfo, TaskEvent
from ray_tpu._private.ids import (
    ActorID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from ray_tpu._private.raylet import (
    Raylet,
    RemoteRaylet,
    RemoteStoreProxy,
    WorkerHandle,
)
from ray_tpu._private.scheduler import (
    ClusterScheduler,
    Infeasible,
    PlacementGroupInfo,
)
from ray_tpu._private.task_spec import (
    ERROR_META,
    TaskResult,
    TaskSpec,
    TaskStatus,
    TaskType,
)


class Head:
    def __init__(self, session_dir: Optional[str] = None, tcp_port: int = 0):
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="ray_tpu_")
        os.makedirs(self.session_dir, exist_ok=True)
        self.socket_path = os.path.join(self.session_dir, "head.sock")
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)  # stale socket from a dead head
        # Persistent cluster identity: a restarted head must present the
        # SAME authkey or reconnecting agents/workers/drivers fail their
        # HMAC handshake (reference: the GCS's stable redis-backed
        # identity).  tcp_port=0 keeps the ephemeral-port behavior for
        # in-process test clusters; a standalone head passes a fixed port.
        keyfile = os.path.join(self.session_dir, "authkey.bin")
        if os.path.exists(keyfile):
            with open(keyfile, "rb") as f:
                self.authkey = f.read()
        else:
            self.authkey = os.urandom(16)
            fd = os.open(keyfile, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                         0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(self.authkey)
        self.gcs = GCS()
        self.scheduler = ClusterScheduler()
        self.raylets: Dict[NodeID, Raylet] = {}
        self._lock = threading.RLock()
        # task_id -> spec for everything in flight (pending or running)
        self.pending: deque = deque()  # specs with no feasible placement yet
        self.running: Dict[TaskID, Tuple[TaskSpec, WorkerID]] = {}
        # Deferred replies: task_id -> list of callbacks fired on completion
        self._object_waiters: Dict[ObjectID, List[Callable[[dict], None]]] = defaultdict(list)
        self._actor_waiters: Dict[ActorID, List[Callable[[dict], None]]] = defaultdict(list)
        self._pg_waiters: Dict[PlacementGroupID, List[Callable[[dict], None]]] = defaultdict(list)
        self._conns: Dict[WorkerID, Any] = {}
        self._conn_worker: Dict[int, WorkerID] = {}
        # Worker registrations that raced ahead of their node's (re-)
        # registration during head failover: replayed in add_remote_node.
        self._pending_worker_regs: Dict[NodeID, list] = defaultdict(list)
        self._pending_pgs: List[PlacementGroupInfo] = []
        # Arena reader leases: oid -> {holder worker id: count}.  Granted when
        # an arena resolution is handed to a reader, released when the reader
        # drops its last zero-copy view.  The equivalent of plasma's client
        # in-use counts (the reference never reuses memory while a client
        # holds the buffer): an arena slot must not be recycled while any
        # process may still read it.
        self._arena_leases: Dict[ObjectID, Dict[bytes, int]] = defaultdict(dict)
        self._arena_pending_free: set = set()
        self._cancelled: set = set()  # task ids cancelled while running
        # task id -> host usage fraction at kill time (memory-monitor
        # victims, head- or agent-side): the death handler surfaces a
        # typed OutOfMemoryError carrying the usage once retries run out.
        self._oom_killed: Dict[TaskID, float] = {}
        # Nodes declared dead exactly once: conn EOF, lease expiry, and
        # explicit kills all funnel through remove_node, which must not
        # double-run death processing (reference: the GCS node manager's
        # single DEAD transition, gcs_node_manager.h).
        self._dead_nodes: set = set()
        self._shutdown = False
        # Idempotency-key reply cache: retried/duplicated request frames
        # (client resends after a lost reply, chaos dup injection,
        # reconnect resends) are applied exactly once — duplicates attach
        # to the original execution and are answered from its reply.
        from ray_tpu._private.config import CONFIG as _CONFIG
        from ray_tpu._private.retry import ReplyCache

        self._rpc_cache = ReplyCache(
            cap=_CONFIG.rpc_reply_cache_size,
            ttl=_CONFIG.rpc_reply_cache_ttl_s)
        # ---- tracing plane ----
        # Cluster span sink: workers flush span batches here (span_batch
        # op / node_stats piggyback); byte-budgeted so tracing can stay
        # on without unbounded head memory.  The event log is the flight
        # recorder's "what happened lately" feed (node joins/deaths,
        # kills) — cheap enough to run even with tracing off.
        from ray_tpu.observability.trace_store import TraceStore

        self.trace_store = TraceStore(
            max_bytes=_CONFIG.trace_store_max_bytes,
            per_trace_bytes=_CONFIG.trace_max_bytes)
        self._event_log: deque = deque(maxlen=512)
        # ---- multi-host plane ----
        # Host identity: object resolutions are host-aware — same host means
        # "attach the shm segment", different host means "pull over TCP from
        # the owning store" (reference: object_manager.h:117 push/pull).
        self.host_key = os.urandom(8).hex()
        self.node_host: Dict[NodeID, str] = {}       # node -> host key
        self.node_xfer: Dict[NodeID, tuple] = {}      # node -> (ip, port)
        self._local_xfer: Dict[NodeID, Any] = {}      # local transfer servers
        # Cooperative-broadcast reverse index: partial-holder key (worker
        # id / node key) -> oids it advertised, so a process death clears
        # its advertisements in O(its objects), not O(all objects).
        self._partial_index: Dict[bytes, set] = defaultdict(set)
        self._driver_hosts: Dict[bytes, str] = {}     # remote driver host keys
        self._driver_nodes: Dict[bytes, NodeID] = {}  # driver wid -> pseudo node
        self._driver_conns: Dict[bytes, Any] = {}     # driver wid -> live conn
        self._has_remote = False
        self._listener = Listener(self.socket_path, family="AF_UNIX",
                                  authkey=self.authkey)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="rtpu-accept", daemon=True)
        self._accept_thread.start()
        # TCP listener: remote node agents, remote drivers, and workers on
        # remote nodes all connect here (the networked flank of the same
        # control protocol the AF_UNIX listener speaks).  Binds loopback by
        # default — a purely local cluster must not expose its control
        # plane on external interfaces; set RAY_TPU_TCP_HOST=0.0.0.0 when
        # remote hosts are expected to join.
        from ray_tpu._private.config import CONFIG

        self.tcp_bind_host = CONFIG.tcp_host
        self._tcp_listener = Listener((self.tcp_bind_host, tcp_port),
                                      family="AF_INET", authkey=self.authkey)
        self.tcp_port = self._tcp_listener.address[1]
        self._tcp_accept_thread = threading.Thread(
            target=self._accept_loop,
            kwargs={"listener": self._tcp_listener,
                    "thread_name": "rtpu-conn-tcp"},
            name="rtpu-accept-tcp", daemon=True)
        self._tcp_accept_thread.start()
        # Health monitor: catches worker processes that die before/without
        # closing their connection (e.g. failed to start at all) — the
        # equivalent of the reference's GCS health checks
        # (gcs_health_check_manager.h:39).
        # Memory-pressure policing (reference: memory_monitor.h:52 +
        # worker_killing_policy.h:33): evaluated from the same monitor loop.
        from ray_tpu._private.memory_monitor import MemoryMonitor

        self.memory_monitor = MemoryMonitor(self)
        self._monitor_thread = threading.Thread(target=self._monitor_loop,
                                                name="rtpu-monitor", daemon=True)
        self._monitor_thread.start()
        # Worker log capture → GCS pubsub → driver echo (reference:
        # log_monitor.py:104).
        from ray_tpu._private.log_monitor import LogMonitor

        self.log_monitor = LogMonitor(os.path.join(self.session_dir, "logs"),
                                      self.gcs)
        # GCS persistence (reference: RedisStoreClient-backed GCS FT,
        # redis_store_client.h:28): restore durable tables from a prior
        # snapshot in this session dir, and re-snapshot periodically when
        # gcs_snapshot_period_s > 0.
        self.gcs_snapshot_path = os.path.join(self.session_dir,
                                              "gcs_snapshot.pkl")
        self.gcs.load_snapshot(self.gcs_snapshot_path)
        # ---- arg-locality plane (place compute where the bytes live) ----
        # Tasks with directory-tracked ObjectRef args park until the args
        # exist somewhere, so placement sees real per-host byte counts;
        # the default policy then prefers the holder host, and args still
        # missing from the chosen host are prefetched into its store
        # while the task is queued (initialized BEFORE snapshot restore —
        # restored creation specs go through _schedule below).
        self._locality_on: bool = CONFIG.locality_scheduling
        self._locality_prefetch: bool = (self._locality_on
                                         and CONFIG.locality_prefetch)
        self._dep_parked: Dict[ObjectID, List[TaskSpec]] = defaultdict(list)
        self._prefetch_inflight: set = set()          # {(oid, node_id)}
        self._prefetch_recs: Dict[tuple, dict] = {}   # in-flight records
        self._prefetch_log: deque = deque(maxlen=256)  # wall-stamp proof
        self._prefetch_q = None                       # lazy worker queue
        self._loc_counters: Dict[str, float] = {}     # sched_locality_*
        # Restored actors that had NO worker at snapshot time (creation
        # still queued) have nothing to re-adopt: reschedule their
        # creation now — it waits in the pending queue until capacity
        # (re-)registers.
        with self._lock:
            from ray_tpu._private.gcs import ActorState as _AS

            for info in self.gcs.actors.values():
                if info.state == _AS.RESTARTING \
                        and info.reconnect_worker_id is None:
                    self._schedule(info.creation_spec)
        self._boot_time = __import__("time").monotonic()
        self._reconnect_reaped = False
        # ---- object durability plane (node-loss survivability) ----
        # Puts have no lineage: without a second copy they die with their
        # node.  object_durability=replicate:K keeps K async replicas on
        # distinct holder nodes; =spill keeps an on-disk backup the head
        # can restore from.  Off by default — the fault-free hot path
        # pays only one predicate check per seal.
        self._durability: Optional[tuple] = None
        spec = (CONFIG.object_durability or "off").strip().lower()
        if spec.startswith("replicate"):
            k = 2
            if ":" in spec:
                try:
                    k = max(2, int(spec.split(":", 1)[1]))
                except ValueError:
                    pass
            self._durability = ("replicate", k)
        elif spec == "spill":
            self._durability = ("spill",)
        self._durability_min = CONFIG.object_durability_min_bytes
        self._durability_q = None
        self._durability_pending = 0  # queued + in-flight (quiesce gate)
        self._repl_client = None  # lazy TransferClient for replica pulls
        if self._durability is not None:
            import queue as _queue

            self._durability_q = _queue.Queue()
            threading.Thread(target=self._durability_loop,
                             name="rtpu-durability", daemon=True).start()
        period = CONFIG.gcs_snapshot_period_s
        if period > 0:
            def snapshot_loop():
                import time as _time

                while not self._shutdown:
                    _time.sleep(period)
                    try:
                        self.gcs.save_snapshot(self.gcs_snapshot_path)
                    except Exception:
                        pass

            threading.Thread(target=snapshot_loop, name="rtpu-gcs-snap",
                             daemon=True).start()

    def _monitor_loop(self):
        import time as _time

        from ray_tpu._private.config import CONFIG

        # The loop paces both worker-liveness checks and memory-pressure
        # ticks: honor the faster of the two periods so a sub-500ms
        # memory_monitor_refresh_ms is actually achieved.
        period = CONFIG.health_check_period_s
        if self.memory_monitor.enabled:
            period = min(period, self.memory_monitor.period_s)
        period = max(0.02, period)  # floor: never busy-spin the head lock
        stats_period = CONFIG.node_stats_period_s
        last_stats = 0.0
        while not self._shutdown:
            _time.sleep(period)
            # Local node stats (reference: the per-node reporter agent;
            # local raylets share this host, so one host snapshot + each
            # raylet's own store stats).  Remote nodes report over their
            # agent connection instead.
            now = _time.monotonic()
            if stats_period > 0 and now - last_stats >= stats_period:
                last_stats = now
                from ray_tpu._private.node_stats import (collect_node_stats,
                                                         host_snapshot)
                from ray_tpu._private.raylet import RemoteRaylet

                base = host_snapshot()  # ONE cpu/mem read per tick —
                # local raylets share this host (per-raylet cpu_percent
                # calls would measure microsecond intervals)
                from ray_tpu._private.recovery import recovery_stats

                rec = recovery_stats()  # cluster-level recovery counters:
                # exported on the head's own node row so chaos runs can
                # assert recovery happened from node_stats/dashboard
                with self._lock:
                    first_local = True
                    for raylet in self.raylets.values():
                        if isinstance(raylet, RemoteRaylet):
                            continue
                        stats = collect_node_stats(
                            store=raylet.store,
                            num_workers=len(raylet.workers),
                            host_base=base)
                        if first_local:
                            first_local = False
                            stats.update(rec)
                        self.gcs.update_node_stats(raylet.node_id, stats)
            # Agent lease expiry: a remote node whose heartbeat went
            # silent past the lease is declared dead exactly once — its
            # locations are discarded (recovery paths take over), its
            # leased/queued work is requeued, its workers struck
            # (reference: gcs_health_check_manager.h node failure).
            lease = CONFIG.node_lease_timeout_s
            if lease > 0:
                expired = []
                now = _time.monotonic()
                with self._lock:
                    for nid, raylet in self.raylets.items():
                        if not isinstance(raylet, RemoteRaylet) \
                                or raylet.max_workers <= 0:
                            continue  # local nodes + driver pseudo-nodes
                        info = self.gcs.nodes.get(nid)
                        if info is not None \
                                and now - info.last_heartbeat > lease:
                            expired.append(nid)
                for nid in expired:
                    self.remove_node(
                        nid, cause=f"agent lease expired (no heartbeat "
                                   f"for {lease:.0f}s)")
            with self._lock:
                self._reap_unreconnected_actors()
                self.memory_monitor.tick()
                for raylet in list(self.raylets.values()):
                    for h in list(raylet.workers.values()):
                        if h.proc is not None and h.proc.poll() is not None:
                            if h.conn is None:
                                raylet.num_starting = max(0, raylet.num_starting - 1)
                                raylet.consecutive_start_failures += 1
                            self._handle_worker_death(
                                h, f"worker process exited with code "
                                   f"{h.proc.returncode}")
                            raylet.on_worker_lost(h.worker_id)
                            self._conns.pop(h.worker_id, None)
                            if raylet.consecutive_start_failures >= 3:
                                # Workers can't start at all (e.g. broken env):
                                # fail queued work instead of spawn-looping.
                                while raylet.queued:
                                    spec = raylet.queued.popleft()
                                    self.scheduler.return_resources(
                                        raylet.node_id, spec)
                                    self._fail_task(spec, exc.WorkerCrashedError(
                                        "worker processes repeatedly failed "
                                        "to start on this node"))
                            else:
                                raylet.try_dispatch()

    @property
    def tcp_address(self) -> str:
        if self.tcp_bind_host not in ("0.0.0.0", "::"):
            return f"{self.tcp_bind_host}:{self.tcp_port}"
        from ray_tpu._private.transfer import routable_ip

        return f"{routable_ip()}:{self.tcp_port}"

    # ================= cluster membership =================
    def add_node(self, resources: Dict[str, float], labels: Optional[dict] = None,
                 store_capacity: int = 2 * 1024**3, max_workers: int = 64) -> NodeID:
        node_id = NodeID.from_random()
        with self._lock:
            raylet = Raylet(node_id, self, store_capacity, labels, max_workers,
                            tpu_chips=int(resources.get("TPU", 0)))
            raylet.store.evict_callback = (
                lambda oid, nid=node_id: self._on_object_evicted(oid, nid))
            # Spill policy: only objects the directory still references are
            # worth the disk write; the rest just evict (reference:
            # LocalObjectManager spills pinned/referenced objects,
            # local_object_manager.h:41).
            raylet.store.should_spill = self._object_is_referenced
            # Directory-side spill records: the head must know about every
            # on-disk copy so it can serve restores after the owning
            # store (node) dies — and so the record survives a head
            # restart via the GCS snapshot.
            raylet.store.spill_callback = (
                lambda oid, nid=node_id: self._on_local_spill(oid, nid))
            self.raylets[node_id] = raylet
            self.node_host[node_id] = self.host_key
            self.scheduler.add_node(node_id, resources, labels)
            self.gcs.register_node(NodeInfo(node_id, resources, labels))
            if self._has_remote:
                self._ensure_local_transfer(node_id)
            self._drain_pending()
            self._drive_pending_pgs()
        return node_id

    def add_remote_node(self, msg: dict, conn) -> NodeID:
        """A node agent registered over TCP: attach its host to the cluster
        (reference: raylet self-registration with the GCS).  A
        RE-registration after head failover carries the agent's previous
        node_id and its surviving worker processes, which are adopted
        rather than respawned."""
        from ray_tpu._private.config import CONFIG

        node_id = (NodeID(msg["node_id"]) if msg.get("node_id")
                   else NodeID.from_random())
        resources = dict(msg["resources"])
        labels = msg.get("labels") or {}
        with self._lock:
            # A healed partition may re-register a node the lease expiry
            # already declared dead: it rejoins as a live node and must be
            # removable again.
            self._dead_nodes.discard(node_id)
            raylet = RemoteRaylet(
                node_id, self, conn, msg["host_key"], msg["transfer_addr"],
                labels, msg.get("max_workers", 64),
                tpu_chips=int(resources.get("TPU", 0)))
            self.raylets[node_id] = raylet
            self.node_host[node_id] = msg["host_key"]
            self.node_xfer[node_id] = tuple(msg["transfer_addr"])
            self._has_remote = True
            # Local stores must now be pull-servable by remote hosts.
            for nid in list(self.raylets):
                self._ensure_local_transfer(nid)
            self.scheduler.add_node(node_id, resources, labels)
            self.gcs.register_node(NodeInfo(node_id, resources, labels))
            # Adopt the agent's surviving worker processes (failover):
            # handles exist immediately; each worker's own reconnect then
            # attaches its control conn (possibly already parked below).
            from ray_tpu._private.raylet import _RemoteProc, WorkerHandle

            for w in msg.get("workers") or []:
                if isinstance(w, dict):
                    wid = WorkerID(w["worker_id"])
                    chips = tuple(w.get("tpu_chips") or ())
                else:  # bare worker-id (older agents)
                    wid, chips = WorkerID(w), ()
                h = WorkerHandle(wid, _RemoteProc(raylet, wid), node_id)
                if chips:
                    # The surviving worker still owns these chips: keep
                    # them out of the fresh raylet's free pool.
                    h.tpu_visible = True
                    h.tpu_chips = chips
                    raylet._free_chips = [c for c in raylet._free_chips
                                          if c not in chips]
                raylet.workers[wid] = h
            for worker_id, wconn, daddr in self._pending_worker_regs.pop(
                    node_id, []):
                self._conns[worker_id] = wconn
                h = raylet.on_worker_registered(worker_id, wconn, daddr)
                self._try_readopt_actor(raylet, node_id, worker_id, h)
            self._drain_pending()
            self._drive_pending_pgs()
        self._send_on(conn, {"type": "node_registered",
                             "node_id": node_id.binary(),
                             # Head-resolved config the agent must honor
                             # (its own CONFIG never sees the head's
                             # _system_config overrides).
                             "node_stats_period_s":
                                 CONFIG.node_stats_period_s})
        return node_id

    def add_remote_driver(self, msg: dict, conn) -> NodeID:
        """A remote driver joined over TCP.  It carries its own embedded
        store + transfer server (so its puts stay host-local and stay
        pullable), surfaced here as an unschedulable pseudo-node."""
        node_id = NodeID.from_random()
        worker_id = msg["worker_id"]
        with self._lock:
            raylet = RemoteRaylet(node_id, self, conn, msg["host_key"],
                                  msg["transfer_addr"], max_workers=0)
            self.raylets[node_id] = raylet
            self.node_host[node_id] = msg["host_key"]
            self.node_xfer[node_id] = tuple(msg["transfer_addr"])
            self._has_remote = True
            for nid in list(self.raylets):
                self._ensure_local_transfer(nid)
            self._driver_hosts[worker_id] = msg["host_key"]
            self._driver_nodes[worker_id] = node_id
            self._driver_conns[worker_id] = conn
            self.gcs.add_job(msg["job_id"], msg.get("job_config") or {})
        self._send_on(conn, {"type": "driver_registered",
                             "node_id": node_id.binary()})
        return node_id

    def _ensure_local_transfer(self, node_id: NodeID):
        """Start a transfer server over a local raylet's store (idempotent;
        only local stores need one here — remote stores bring their own)."""
        if node_id in self._local_xfer or node_id in self.node_xfer:
            return
        raylet = self.raylets.get(node_id)
        if raylet is None or isinstance(raylet.store, RemoteStoreProxy):
            return
        from ray_tpu._private.transfer import ObjectTransferServer

        srv = ObjectTransferServer(raylet.store, self.authkey)
        self._local_xfer[node_id] = srv
        self.node_xfer[node_id] = srv.address

    def remove_node(self, node_id: NodeID, cause: str = "node removed"):
        """Node-death protocol — one authority for every death signal
        (agent conn EOF, lease expiry, chaos kill, explicit removal).
        Exactly once per node: discard its object locations (surviving
        replicas / spill records / lineage take over), requeue work that
        was queued-but-never-started there, run worker-death processing
        for every worker (running-task retries, lease reclaim, actor FSM,
        rollout-worker strikes via ActorDiedError), and fail objects with
        no recovery path so waiters error instead of hanging forever."""
        from ray_tpu._private.recovery import note

        with self._lock:
            if node_id in self._dead_nodes:
                return
            self._dead_nodes.add(node_id)
            self._log_event("node_death", node=node_id.hex(), cause=cause)
            # Flight recorder: snapshot BEFORE death processing reshuffles
            # the task table, so the bundle shows what was running (and
            # which spans the victim flushed) at the moment of death.
            self._flight_snapshot(
                f"node_death_{node_id.hex()[:8]}",
                {"cause": cause, "node": node_id.hex()})
            raylet = self.raylets.pop(node_id, None)
            # PGs demoted to PENDING by the node loss re-reserve through
            # the pending queue once capacity returns (their surviving
            # bundles' reservations were released by the scheduler).
            for pg in self.scheduler.remove_node(node_id):
                if pg not in self._pending_pgs:
                    self._pending_pgs.append(pg)
            # Prefetches targeting the dead node can never complete.
            for key in [k for k in self._prefetch_inflight
                        if k[1] == node_id]:
                self._finish_prefetch(key, 0, False)
            self.gcs.remove_node(node_id)
            self.node_host.pop(node_id, None)
            self.node_xfer.pop(node_id, None)
            self._drop_partials_for(b"na:" + node_id.binary())
            srv = self._local_xfer.pop(node_id, None)
            if srv is not None:
                srv.shutdown()
            if raylet is None:
                return
            if raylet.max_workers > 0:  # driver pseudo-nodes don't count
                note("node_deaths")
            # Queued-but-never-started specs: their node (and its held
            # resources) died with them — reschedule cluster-wide with no
            # attempt charged, they never ran.
            queued, raylet.queued = list(raylet.queued), deque()
            # All workers on the node die.  Their conns are left to the
            # EOF teardown path (on_conn_closed), which reclaims each
            # worker's held references and leases exactly as for a lone
            # worker death.
            for h in list(raylet.workers.values()):
                self._handle_worker_death(h, f"{cause}: node is dead")
            for spec in queued:
                self._schedule(spec)
            # Tear the store down BEFORE reconstruction: a reconstructed
            # task re-creating an output must not collide with (or be
            # resolved against) the dead store's still-linked segments.
            # Spill files survive — they are the durability plane's
            # restore source.
            raylet.shutdown(keep_spilled=True)
            # Objects on the node are lost; recovery order: surviving
            # replica location > lineage reconstruction > spill restore >
            # typed ObjectLostError (never a silent hang).
            for oid, entry in list(self.gcs.objects.items()):
                if node_id not in entry.locations:
                    continue
                entry.locations.discard(node_id)
                entry.segments.pop(node_id, None)
                if entry.inline is not None:
                    continue
                if entry.locations:
                    note("objects_restored")  # a replica carries it
                    continue
                # Mark lost BEFORE recovery: recovery paths that complete
                # (restore, output reconstruct) clear it; an in-flight
                # put re-run leaves it set so _fail_task can fail the put
                # typed if the re-run can never schedule, and get-side
                # probes keep re-entering _try_reconstruct meanwhile.
                entry.lost = True
                if not self._try_reconstruct(oid, entry):
                    self._fail_object_locked(oid, exc.ObjectLostError(
                        f"object {oid} was lost with its node ({cause}) "
                        "and has no lineage, replica, or spill copy to "
                        "recover from"))
            self._drain_pending()
            self._drive_pending_pgs()

    def kill_node(self, node_id: NodeID):
        """Chaos: SIGKILL every worker process on the node, then run the
        node-death protocol — the in-process equivalent of SIGKILLing a
        node agent and its children (no graceful store drain, no worker
        shutdown handshake)."""
        with self._lock:
            raylet = self.raylets.get(node_id)
            if raylet is None:
                return
            self._log_event("kill_node", node=node_id.hex())
            for h in list(raylet.workers.values()):
                try:
                    h.proc.kill()
                except Exception:
                    pass
        self.remove_node(node_id, cause="node killed (chaos)")

    def _fail_object_locked(self, oid: ObjectID, error: BaseException):
        """No recovery path: record the error as the object's value so
        every current waiter and future get raises it (reference: owner
        failure => ObjectLostError, never an indefinite hang)."""
        from ray_tpu._private.recovery import note

        note("objects_lost")
        meta, data = _serialize_error(error)
        self._record_error_result(oid, (meta, data))

    def _on_local_spill(self, oid: ObjectID, node_id: NodeID):
        """A local raylet store wrote a spill/backup file: mirror the
        record into the directory so it can outlive the store (node
        death restore) and the head process (GCS snapshot)."""
        raylet = self.raylets.get(node_id)
        if raylet is None:
            return
        rec = raylet.store.spilled_lookup(oid)
        if rec is not None:
            self.gcs.object_spill_recorded(oid, rec["path"], rec["meta"],
                                           rec["size"], host=None)

    # ================= worker connections =================
    def _accept_loop(self, listener=None, thread_name: str = "rtpu-conn"):
        listener = listener or self._listener
        while not self._shutdown:
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                return
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name=thread_name, daemon=True)
            t.start()

    def _send_on(self, conn, msg) -> bool:
        """Send on a worker/agent/driver connection under its per-conn lock.

        Multiple head threads write to the same Connection (request
        replies, execute pushes, store ops to agents); an unserialized
        multi-chunk send would interleave bytes and corrupt the stream."""
        lock = getattr(conn, "_rtpu_send_lock", None)
        try:
            if lock is not None:
                with lock:
                    conn.send(msg)
            else:
                conn.send(msg)
            return True
        except Exception:
            return False

    def _conn_loop(self, conn):
        conn._rtpu_send_lock = threading.Lock()
        worker_id: Optional[WorkerID] = None
        agent_node: Optional[NodeID] = None
        driver_wid: Optional[bytes] = None
        try:
            while True:
                msg = conn.recv()
                mtype = msg.get("type")
                if agent_node is not None:
                    # Any traffic from an agent refreshes its liveness
                    # lease; the dedicated "heartbeat" frames just bound
                    # the silence of an otherwise-idle node.
                    self.gcs.touch_node(agent_node)
                if mtype == "register":
                    worker_id = WorkerID(msg["worker_id"])
                    self._on_register(worker_id, NodeID(msg["node_id"]), conn,
                                      msg.get("direct_addr"))
                elif mtype == "register_node":
                    agent_node = self.add_remote_node(msg, conn)
                elif mtype == "register_driver":
                    driver_wid = msg["worker_id"]
                    worker_id = WorkerID(driver_wid)
                    self.add_remote_driver(msg, conn)
                elif mtype == "worker_exit":
                    if agent_node is not None:
                        self.on_remote_worker_exit(agent_node, msg)
                elif mtype == "node_stats":
                    if agent_node is not None:
                        self.gcs.update_node_stats(agent_node,
                                                   msg.get("stats") or {})
                        spans = msg.get("spans")
                        if spans:
                            # Agent-relayed span batch riding the stats
                            # cadence (its own ring + worker leftovers).
                            self.trace_store.ingest(spans)
                elif mtype == "heartbeat":
                    pass  # touch_node above already refreshed the lease
                elif mtype == "worker_oom":
                    if agent_node is not None:
                        self.on_worker_oom(WorkerID(msg["worker_id"]),
                                           float(msg.get("usage", 0.0)))
                elif mtype == "object_replicated":
                    if agent_node is not None:
                        self.on_object_replicated(agent_node, msg)
                elif mtype == "object_partial":
                    if agent_node is not None:
                        host = self.node_host.get(agent_node)
                    elif driver_wid is not None:
                        host = self._driver_hosts.get(driver_wid)
                    else:
                        host = self._caller_host(worker_id)
                    self.on_object_partial(msg, host)
                elif mtype == "object_partial_drop":
                    self.on_object_partial_drop(msg)
                elif mtype == "object_evicted":
                    nid = agent_node or (driver_wid and
                                         self._driver_nodes.get(driver_wid))
                    if nid is not None:
                        with self._lock:
                            self._on_object_evicted(ObjectID(msg["oid"]), nid)
                elif mtype == "object_spilled":
                    nid = agent_node or (driver_wid and
                                         self._driver_nodes.get(driver_wid))
                    if nid is not None:
                        with self._lock:
                            raylet = self.raylets.get(nid)
                            if raylet is not None and isinstance(
                                    raylet.store, RemoteStoreProxy):
                                raylet.store.note_spilled(
                                    ObjectID(msg["oid"]), msg["path"],
                                    msg["meta"], msg["size"])
                            # Directory-side copy of the record, tagged
                            # with the owning host: same-host restores
                            # survive the proxy (and the node row) dying.
                            self.gcs.object_spill_recorded(
                                ObjectID(msg["oid"]), msg["path"],
                                msg["meta"], msg["size"],
                                host=self.node_host.get(nid))
                elif mtype == "task_done":
                    self.on_task_done(msg)
                elif mtype == "worker_blocked":
                    self.on_worker_blocked(WorkerID(msg["worker_id"]))
                elif mtype == "worker_unblocked":
                    self.on_worker_unblocked(WorkerID(msg["worker_id"]))
                elif mtype == "seal":
                    self.on_seal(msg)
                elif mtype == "put_inline":
                    self.on_put_inline(msg)
                elif mtype == "seal_batch":
                    self.on_seal_batch(msg)
                elif mtype == "put_inline_batch":
                    self.on_put_inline_batch(msg)
                elif mtype == "arena_release":
                    self.on_arena_release(msg)
                elif mtype == "request":
                    self._handle_request(msg, conn, worker_id)
                elif mtype == "notify":
                    # One-way request: no reply frame (hot-path submits).
                    try:
                        tc = msg.get("tc")
                        if tc is not None and self._tracing_on():
                            from ray_tpu import observability as obs

                            with obs.use_context(tuple(tc)):
                                self.handle_request(
                                    msg["op"], msg.get("payload") or {},
                                    lambda *a, **k: None, worker_id)
                        else:
                            self.handle_request(
                                msg["op"], msg.get("payload") or {},
                                lambda *a, **k: None, worker_id)
                    except Exception:
                        traceback.print_exc()
        except (EOFError, OSError, BrokenPipeError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            # Teardown is identity-checked: a peer that already
            # RE-registered over a fresh connection (head failover /
            # transient drop) must not be torn down by its old socket's
            # delayed EOF.
            if agent_node is not None:
                raylet = self.raylets.get(agent_node)
                if raylet is not None \
                        and getattr(raylet, "agent_conn", None) is conn:
                    self.remove_node(agent_node)
            elif driver_wid is not None:
                if self._driver_conns.get(driver_wid) is conn:
                    self.on_driver_disconnected(driver_wid)
            elif worker_id is not None:
                if self._conns.get(worker_id) is conn:
                    self.on_conn_closed(worker_id)

    def on_remote_worker_exit(self, node_id: NodeID, msg: dict):
        """Agent reported one of its worker subprocesses exited — mirrors
        the local health-monitor poll path."""
        with self._lock:
            raylet = self.raylets.get(node_id)
            if raylet is None:
                return
            h = raylet.workers.get(WorkerID(msg["worker_id"]))
            if h is None:
                return
            h.proc.returncode = msg.get("code", -1)
            if h.conn is None:
                raylet.num_starting = max(0, raylet.num_starting - 1)
                raylet.consecutive_start_failures += 1
            self._handle_worker_death(
                h, f"worker process exited with code {msg.get('code')}")
            raylet.on_worker_lost(h.worker_id)
            self._conns.pop(h.worker_id, None)
            raylet.try_dispatch()

    def on_worker_oom(self, worker_id: WorkerID, usage: float):
        """A node agent's memory monitor is about to kill (or just killed)
        one of its workers: mark the victim's running task so its death
        surfaces as a typed, retryable OutOfMemoryError instead of a
        generic WorkerCrashedError (the head-side monitor marks its own
        victims the same way in memory_monitor.tick)."""
        from ray_tpu._private.recovery import note

        with self._lock:
            _, h = self._find_worker(worker_id)
            if h is None or h.current_task is None:
                return
            note("oom_worker_kills")
            self._oom_killed[h.current_task.task_id] = usage

    def on_object_replicated(self, node_id: NodeID, msg: dict):
        """An agent finished pulling a durability replica into its store:
        register the new location (readers on that host resolve the
        replica's own segment name, never the primary's)."""
        from ray_tpu._private.recovery import note

        oid = ObjectID(msg["oid"])
        with self._lock:
            key = (oid, node_id)
            was_prefetch = key in self._prefetch_inflight
            if node_id not in self.raylets:
                if was_prefetch:
                    self._finish_prefetch(key, msg["size"], False)
                return  # replica landed after the node died: useless
            self.gcs.object_sealed(oid, node_id, msg["size"],
                                   meta=msg.get("meta"),
                                   segment=msg.get("segment"))
            note("objects_replicated")
            if was_prefetch:
                self._finish_prefetch(key, msg["size"], True)
            # Same-host waiters (e.g. a queued task's worker about to
            # resolve this arg) can now attach the replica segment.
            self._notify_object(oid)

    def on_driver_disconnected(self, driver_wid: bytes):
        with self._lock:
            self._driver_hosts.pop(driver_wid, None)
            self._driver_conns.pop(driver_wid, None)
            self._drop_partials_for(driver_wid)
            node_id = self._driver_nodes.pop(driver_wid, None)
        if node_id is not None:
            self.remove_node(node_id)
        freed = self.gcs.remove_all_references(driver_wid)
        with self._lock:
            self._reclaim_lessee_locked(driver_wid)
            for oid in freed:
                self._free_object(oid)
            self._drain_pending()
            self._drive_pending_pgs()

    def _reclaim_lessee_locked(self, lessee: bytes):
        """Lessee (worker or remote driver) died: release every worker
        lease it held plus its arena leases — leaked leases are permanent
        capacity loss (reference: lease reclaim on lessee death,
        lease_policy / raylet).  Under the head lock."""
        for raylet in self.raylets.values():
            for h in list(raylet.workers.values()):
                if h.leased_to == lessee:
                    self._release_lease_locked(raylet, h)
        self._drop_arena_leases_for(lessee)

    def _on_register(self, worker_id: WorkerID, node_id: NodeID, conn,
                     direct_addr=None):
        with self._lock:
            self._conns[worker_id] = conn
            raylet = self.raylets.get(node_id)
            if raylet is None:
                # Failover race: this worker's node agent has not
                # re-registered yet — park the registration.
                self._pending_worker_regs[node_id].append(
                    (worker_id, conn, direct_addr))
                return
            h = raylet.on_worker_registered(worker_id, conn, direct_addr)
            self._try_readopt_actor(raylet, node_id, worker_id, h)
            raylet.try_dispatch()

    def _try_readopt_actor(self, raylet, node_id, worker_id, h):
        """Head-failover re-adoption: a surviving actor worker came back —
        re-bind its restored actor record (state intact in the worker
        process) instead of pooling the worker.  Under the head lock."""
        for info in self.gcs.actors.values():
            if info.reconnect_worker_id == worker_id:
                info.reconnect_worker_id = None
                if h is not None:
                    h.actor_id = info.actor_id
                    h.busy = True
                    try:
                        raylet.idle.remove(worker_id)
                    except ValueError:
                        pass
                info.resources_held = True
                self.scheduler.reacquire(node_id, info.creation_spec)
                self.gcs.actor_started(info.actor_id, node_id, worker_id)
                self._notify_actor_waiters(info.actor_id)
                calls, info.pending_calls = info.pending_calls, []
                for call in calls:
                    self._push_actor_task(info, call)
                return

    def _reap_unreconnected_actors(self):
        """After the reconnect window, restored actors whose worker never
        came back go through the normal death path (restart budget or
        DEAD) — called under the head lock from the monitor loop."""
        if self._reconnect_reaped:
            return
        import time as _time

        from ray_tpu._private.config import CONFIG

        if _time.monotonic() - self._boot_time < CONFIG.reconnect_window_s:
            return
        self._reconnect_reaped = True
        # Parked worker registrations whose node never re-registered:
        # close them out (the workers give up their own reconnect loops).
        for regs in self._pending_worker_regs.values():
            for _wid, wconn, _d in regs:
                try:
                    wconn.close()
                except Exception:
                    pass
        self._pending_worker_regs.clear()
        for info in list(self.gcs.actors.values()):
            if info.reconnect_worker_id is None:
                continue
            info.reconnect_worker_id = None
            self._on_actor_worker_death(
                info.actor_id,
                "actor worker did not reconnect after head restart")

    def on_conn_closed(self, worker_id: WorkerID):
        with self._lock:
            self._conns.pop(worker_id, None)
            for raylet in self.raylets.values():
                h = raylet.workers.get(worker_id)
                if h is not None:
                    self._handle_worker_death(h, "worker process died")
                    raylet.on_worker_lost(worker_id)
                    raylet.try_dispatch()
                    break
            self._reclaim_lessee_locked(worker_id.binary())
            freed = self.gcs.remove_all_references(worker_id.binary())
            for oid in freed:
                self._free_object(oid)
            self._drain_pending()
            self._drive_pending_pgs()

    def send_to_worker(self, worker: WorkerHandle, msg: dict):
        if not self._send_on(worker.conn, msg):
            self.on_conn_closed(worker.worker_id)

    # ================= tracing plane =================
    def _tracing_on(self) -> bool:
        from ray_tpu.util.tracing import tracing_enabled

        return tracing_enabled()

    def _drain_local_spans(self) -> None:
        """Pull the head/driver process's own span ring into the store.
        Workers and agents push theirs over the wire; in-process
        emitters (driver spans, head.<op> spans) are drained whenever
        the store is about to be read."""
        if not self._tracing_on():
            return
        from ray_tpu import observability as obs

        spans = obs.drain_spans()
        if spans:
            self.trace_store.ingest(spans)

    def _log_event(self, kind: str, **detail) -> None:
        self._event_log.append({"ts": time.time(), "event": kind,
                                **detail})

    def _flight_snapshot(self, reason: str,
                         extra: Optional[dict] = None) -> Optional[str]:
        """Snapshot rings + task table + event log into a postmortem
        bundle.  No-op unless a flight-record dir is configured; never
        raises into the death path that triggered it."""
        from ray_tpu.observability.flight_recorder import (
            flight_record_dir,
            write_bundle,
        )

        if flight_record_dir() is None:
            return None
        self._drain_local_spans()
        try:
            tasks = self.gcs.list_tasks()
        except Exception:
            tasks = []
        path = write_bundle(reason, spans=self.trace_store.spans(),
                            tasks=tasks, events=list(self._event_log),
                            extra=extra)
        if path is not None:
            self._log_event("flight_record", reason=reason, path=path)
        return path

    def req_span_batch(self, payload, reply, caller):
        """Span flush from a worker/driver: ingest into the TraceStore."""
        spans = payload.get("spans") or []
        if spans:
            self.trace_store.ingest(spans)
        reply(True)

    def req_flight_record(self, payload, reply, caller):
        """Driver-triggered postmortem snapshot (gang restart handlers,
        MeshGroupError paths)."""
        reply(self._flight_snapshot(
            payload.get("reason") or "manual",
            {"trigger": "request"}))

    def req_traces(self, payload, reply, caller):
        self._drain_local_spans()
        reply(self.trace_store.list_traces(
            limit=int(payload.get("limit") or 50)))

    def req_trace_timeline(self, payload, reply, caller):
        """Raw material for timeline assembly: task rows + the trace's
        spans (all spans when no trace_id) — the client merges them with
        observability.timeline.build_chrome_trace."""
        self._drain_local_spans()
        trace_id = payload.get("trace_id")
        with self._lock:
            tasks = self.gcs.list_tasks()
        if trace_id:
            tasks = [t for t in tasks if t.get("trace_id") == trace_id]
        reply({"tasks": tasks,
               "spans": self.trace_store.spans(trace_id or None)})

    def req_span_summary(self, payload, reply, caller):
        self._drain_local_spans()
        reply(self.trace_store.summary())

    # ================= request router =================
    def _handle_request(self, msg: dict, conn, worker_id: Optional[WorkerID]):
        msg_id = msg["msg_id"]
        op = msg["op"]

        def reply(value=None, error: Optional[BaseException] = None):
            # The op is echoed in the reply frame so client-side fault
            # injection and debugging can address replies by op.
            self._send_on(conn, {"type": "reply", "msg_id": msg_id,
                                 "op": op, "ok": error is None,
                                 "value": value, "error": error})

        tc = msg.get("tc")
        if tc is not None and self._tracing_on():
            from ray_tpu import observability as obs

            with obs.use_context(tuple(tc)):
                self.handle_request_keyed(op, msg.get("payload") or {},
                                          reply, worker_id,
                                          msg.get("rpc_key"))
            return
        self.handle_request_keyed(op, msg.get("payload") or {}, reply,
                                  worker_id, msg.get("rpc_key"))

    def handle_request_keyed(self, op: str, payload: dict,
                             reply: Callable[..., None],
                             caller: Optional[WorkerID] = None,
                             key: Optional[bytes] = None):
        """Keyed entry point: frames carrying an idempotency key pass the
        reply cache first — the first frame per key executes, duplicates
        (resends after a dropped reply, chaos dup injection, reconnect
        resends) are answered from the cached/attached reply and never
        re-applied."""
        if key is not None:
            run, wrapped = self._rpc_cache.admit(key, reply)
            if not run:
                return
            reply = wrapped
        try:
            self.handle_request(op, payload, reply, caller)
        except BaseException as e:  # noqa: BLE001 — errors go to the caller
            reply(error=e)

    def handle_request(self, op: str, payload: dict,
                       reply: Callable[..., None],
                       caller: Optional[WorkerID] = None):
        """Single entry point for worker requests AND direct driver calls."""
        fn = getattr(self, "req_" + op, None)
        if fn is None:
            reply(error=ValueError(f"unknown op {op!r}"))
            return
        # Head-side span: records the op inside the caller's trace.
        # Sitting BELOW the reply-cache admit means a resent frame
        # answered from cache never re-records — the resend-dedup
        # guarantee for head spans.  span_batch itself is exempt (the
        # flush path must not generate spans about shipping spans).
        if op != "span_batch" and self._tracing_on():
            from ray_tpu import observability as obs

            if obs.get_context() is not None:
                t0 = time.time()
                try:
                    fn(payload, reply, caller)
                finally:
                    obs.record("head." + op, t0, time.time())
                return
        fn(payload, reply, caller)

    def req_notify_msg(self, payload, reply, caller):
        """Acked notify: a one-way message routed through the keyed
        request path (chaos / rpc_acked_ops), so a dropped seal or
        task_done is retried by its sender and a duplicated frame is
        deduplicated by the reply cache instead of double-applying."""
        msg = payload["msg"]
        t = msg.get("type")
        fn = {
            "seal": self.on_seal,
            "put_inline": self.on_put_inline,
            "seal_batch": self.on_seal_batch,
            "put_inline_batch": self.on_put_inline_batch,
            "task_done": self.on_task_done,
            "arena_sealed": self.on_arena_sealed,
            "arena_release": self.on_arena_release,
            "worker_blocked":
                lambda m: self.on_worker_blocked(WorkerID(m["worker_id"])),
            "worker_unblocked":
                lambda m: self.on_worker_unblocked(WorkerID(m["worker_id"])),
            "object_partial":
                lambda m: self.on_object_partial(m,
                                                 self._caller_host(caller)),
            "object_partial_drop": self.on_object_partial_drop,
        }.get(t)
        if fn is None:
            reply(error=ValueError(f"notify_msg cannot route {t!r}"))
            return
        fn(msg)
        reply(True)

    # ----- ops -----
    def req_submit(self, payload, reply, caller):
        self.submit_task(payload["spec"])
        reply(True)

    def req_resolve_batch(self, payload, reply, caller):
        """Resolve many objects in one round trip: returns {hex: msg} for
        every object that is available RIGHT NOW (arena leases granted as
        in req_get_locations); callers fall back to the blocking per-object
        path for the rest.  Collapses the driver's get([refs...]) from one
        request per ref to one request per batch."""
        caller_host = self._caller_host(caller)
        out = {}
        with self._lock:
            for oid in payload["oids"]:
                resolved = self._resolve_object(oid, caller_host=caller_host)
                if resolved is not None:
                    if resolved.get("kind") == "arena":
                        self._grant_arena_lease(oid, caller)
                    self._note_pull_resolution(resolved)
                    out[oid.binary()] = resolved
        reply(out)

    def req_get_locations(self, payload, reply, caller):
        """Resolve an object: reply immediately if available, else defer."""
        oid: ObjectID = payload["oid"]
        timeout = payload.get("timeout")
        caller_host = self._caller_host(caller)
        with self._lock:
            resolved = self._resolve_object(oid, caller_host=caller_host)
            if resolved is not None:
                if resolved.get("kind") == "arena":
                    self._grant_arena_lease(oid, caller)
                if not payload.get("recheck"):
                    # A puller re-confirming its resolution already paid
                    # the wire-bytes count at the original handout.
                    self._note_pull_resolution(resolved)
                reply(resolved)
                return
            entry = self.gcs.object_lookup(oid)
            if entry is not None and entry.lost:
                if not self._try_reconstruct(oid, entry):
                    reply(error=exc.ObjectLostError(f"{oid} lost and not reconstructable"))
                    return
                # A spill restore completes synchronously (its notify ran
                # before this waiter registered): re-resolve now instead
                # of parking a callback nothing will ever fire.
                resolved = self._resolve_object(oid, caller_host=caller_host)
                if resolved is not None:
                    if resolved.get("kind") == "arena":
                        self._grant_arena_lease(oid, caller)
                    self._note_pull_resolution(resolved)
                    reply(resolved)
                    return
            cb_list = self._object_waiters[oid]
            record = {"done": False}

            def cb(_ready_oid):
                if record["done"]:
                    return
                # Re-resolve for THIS caller's host: different waiters on
                # different hosts need different resolutions.
                resolved_msg = self._resolve_object(oid,
                                                    caller_host=caller_host)
                if resolved_msg is None:
                    return
                record["done"] = True
                if resolved_msg.get("kind") == "arena":
                    self._grant_arena_lease(oid, caller)
                self._note_pull_resolution(resolved_msg)
                reply(resolved_msg)

            cb_list.append(cb)
        if timeout is not None:
            def on_timeout():
                with self._lock:
                    if not record["done"]:
                        record["done"] = True
                        reply(error=exc.GetTimeoutError(f"get({oid}) timed out"))
            t = threading.Timer(timeout, on_timeout)
            t.daemon = True
            t.start()

    def req_wait_ready(self, payload, reply, caller):
        """ray.wait: reply once num_returns of the refs are ready (or timeout).
        Reply value is the set of ready oids at that moment."""
        oids: List[ObjectID] = payload["oids"]
        num_returns = payload["num_returns"]
        timeout = payload.get("timeout")
        state = {"done": False}

        def check_and_reply(locked: bool):
            ready = [o for o in oids if self._resolve_object(o, peek=True) is not None]
            if len(ready) >= num_returns and not state["done"]:
                state["done"] = True
                reply([o.binary() for o in ready])
                return True
            return False

        with self._lock:
            if check_and_reply(True):
                return
            for o in oids:
                if self._resolve_object(o, peek=True) is None:
                    def cb(_msg, _o=o):
                        with self._lock:
                            check_and_reply(True)
                    self._object_waiters[o].append(cb)
        if timeout is not None:
            def on_timeout():
                with self._lock:
                    if not state["done"]:
                        state["done"] = True
                        ready = [o.binary() for o in oids
                                 if self._resolve_object(o, peek=True) is not None]
                        reply(ready)
            t = threading.Timer(timeout, on_timeout)
            t.daemon = True
            t.start()

    def req_add_ref(self, payload, reply, caller):
        holder = payload.get("holder") or (caller.binary() if caller else b"driver")
        self.gcs.add_reference(payload["oid"], holder)
        reply(True)

    def req_remove_ref(self, payload, reply, caller):
        holder = payload.get("holder") or (caller.binary() if caller else b"driver")
        oid = payload["oid"]
        with self._lock:
            if self.gcs.remove_reference(oid, holder):
                self._free_object(oid)
        reply(True)

    def req_remove_ref_batch(self, payload, reply, caller):
        """Coalesced ref drops (the worker's ref-gc drainer): one message
        and one lock acquisition for a burst of K dropped ObjectRefs."""
        holder = payload.get("holder") or (caller.binary() if caller else b"driver")
        with self._lock:
            for oid_bin in payload["oids"]:
                oid = ObjectID(oid_bin)
                if self.gcs.remove_reference(oid, holder):
                    self._free_object(oid)
        reply(True)

    def req_job_config(self, payload, reply, caller):
        from ray_tpu._private.ids import JobID as _JobID

        reply(self.gcs.get_job_config(_JobID(payload["job_id"])))

    def req_kv(self, payload, reply, caller):
        verb = payload["verb"]
        ns = payload.get("namespace", "default")
        if verb == "put":
            reply(self.gcs.kv_put(payload["key"], payload["value"], ns,
                                  payload.get("overwrite", True)))
        elif verb == "get":
            reply(self.gcs.kv_get(payload["key"], ns))
        elif verb == "del":
            self.gcs.kv_del(payload["key"], ns)
            reply(True)
        elif verb == "keys":
            reply(self.gcs.kv_keys(payload.get("prefix", b""), ns))
        else:
            reply(error=ValueError(f"bad kv verb {verb}"))

    def req_create_actor(self, payload, reply, caller):
        spec: TaskSpec = payload["spec"]
        with self._lock:
            self.gcs.register_actor(spec)
            self.submit_task(spec)
        reply(True)

    def req_actor_call(self, payload, reply, caller):
        spec: TaskSpec = payload["spec"]
        self.submit_actor_task(spec, dead_worker=payload.get("dead_worker"))
        reply(True)

    def req_wait_actor_alive(self, payload, reply, caller):
        actor_id: ActorID = payload["actor_id"]
        with self._lock:
            info = self.gcs.get_actor_info(actor_id)
            if info is None:
                reply(error=ValueError(f"unknown actor {actor_id}"))
                return
            if info.state == ActorState.ALIVE:
                reply(True)
                return
            if info.state == ActorState.DEAD:
                reply(error=exc.ActorDiedError(info.death_cause or "actor dead"))
                return
            self._actor_waiters[actor_id].append(reply)

    def req_get_actor(self, payload, reply, caller):
        actor_id = self.gcs.get_named_actor(payload["name"],
                                            payload.get("namespace", "default"))
        if actor_id is None:
            reply(error=ValueError(f"no actor named {payload['name']!r}"))
            return
        info = self.gcs.get_actor_info(actor_id)
        reply({"actor_id": actor_id, "creation_spec": info.creation_spec})

    def req_kill_actor(self, payload, reply, caller):
        self.kill_actor(payload["actor_id"],
                        no_restart=payload.get("no_restart", True))
        reply(True)

    def req_create_pg(self, payload, reply, caller):
        pg = PlacementGroupInfo(payload["pg_id"], payload["bundles"],
                                payload["strategy"], payload.get("name", ""))
        with self._lock:
            if not self.scheduler.pg_feasible(pg):
                pg.state = "INFEASIBLE"
                self.scheduler.placement_groups[pg.pg_id] = pg
                self.gcs.publish("PG", ("INFEASIBLE", pg.pg_id))
                reply(error=exc.PlacementGroupSchedulingError(
                    f"placement group infeasible: {payload['bundles']}"))
                return
            if self.scheduler.create_placement_group(pg):
                self.gcs.publish("PG", ("CREATED", pg.pg_id))
                reply("CREATED")
            else:
                self._pending_pgs.append(pg)
                self._pg_waiters[pg.pg_id].append(reply)

    def req_pg_ready(self, payload, reply, caller):
        pg_id = payload["pg_id"]
        timeout = payload.get("timeout")
        with self._lock:
            pg = self.scheduler.placement_groups.get(pg_id)
            if pg is not None and pg.state == "CREATED":
                reply("CREATED")
                return
            if pg is not None and pg.state == "INFEASIBLE":
                reply(error=exc.PlacementGroupSchedulingError(
                    "placement group is infeasible on this cluster"))
                return
            state = {"done": False}

            def cb(value=None, error=None):
                if not state["done"]:
                    state["done"] = True
                    reply(value, error=error)

            self._pg_waiters[pg_id].append(cb)
        if timeout is not None:
            def on_timeout():
                with self._lock:
                    if not state["done"]:
                        state["done"] = True
                        reply(error=exc.GetTimeoutError("placement group not ready"))
            t = threading.Timer(timeout, on_timeout)
            t.daemon = True
            t.start()

    def req_remove_pg(self, payload, reply, caller):
        with self._lock:
            self.scheduler.remove_placement_group(payload["pg_id"])
            self._pending_pgs = [p for p in self._pending_pgs
                                 if p.pg_id != payload["pg_id"]]
            self._drain_pending()
        reply(True)

    def req_state(self, payload, reply, caller):
        what = payload["what"]
        fn = {
            "actors": self.gcs.list_actors,
            "nodes": self.gcs.list_nodes,
            "tasks": self.gcs.list_tasks,
            "objects": self.gcs.list_objects,
            "jobs": self.gcs.list_jobs,
            "named_actors": self.gcs.list_named_actors,
        }.get(what)
        if fn is None:
            reply(error=ValueError(f"cannot list {what!r}"))
        else:
            reply(fn())

    def req_object_info(self, payload, reply, caller):
        """Directory metadata for an object (size, locations) — used by the
        streaming data executor to convert a store byte budget into an
        in-flight block bound."""
        with self._lock:
            entry = self.gcs.object_lookup(payload["oid"])
            if entry is None:
                reply(None)
                return
            reply({"size": entry.size,
                   "inline": entry.inline is not None,
                   "num_locations": len(entry.locations)})

    def req_cluster_resources(self, payload, reply, caller):
        if payload.get("available"):
            reply(self.scheduler.available_resources())
        else:
            reply(self.scheduler.total_resources())

    def req_cancel(self, payload, reply, caller):
        self.cancel_task(payload["task_id"])
        reply(True)

    # ----- direct transport: leases + actor addresses -----
    def req_lease_worker(self, payload, reply, caller):
        """Grant the caller a worker lease for a scheduling class: pick a
        node + idle worker, hold the resources for the lease's lifetime, and
        hand back the worker's direct address.  None = nothing available
        right now (caller falls back to the classic path and retries).
        Reference: raylet lease grant, node_manager.cc:1817 + lease caching
        in direct_task_transport.h:57."""
        from ray_tpu._private.ids import JobID as _JobID

        spec = TaskSpec(task_id=TaskID.from_random(), job_id=_JobID.nil(),
                        task_type=TaskType.NORMAL, name="__lease__",
                        resources=dict(payload["resources"]))
        with self._lock:
            try:
                node_id = self.scheduler.pick_node(spec)
            except Infeasible as e:
                reply(error=exc.RayTpuError(str(e)))
                return
            if node_id is None:
                reply(None)
                return
            raylet = self.raylets[node_id]
            h = raylet._pop_idle(spec)
            if h is None or h.direct_addr is None:
                if h is not None:  # claimed but not direct-capable
                    raylet.idle.append(h.worker_id)
                raylet.ensure_worker(spec)
                self.scheduler.return_resources(node_id, spec)
                reply(None)
                return
            h.busy = True
            h.leased_to = caller.binary() if caller else b"driver"
            h.lease_spec = spec
            reply({"worker_id": h.worker_id.binary(),
                   "addr": h.direct_addr})

    def _release_lease_locked(self, raylet, h):
        if h.leased_to is None:
            return
        if h.blocked:
            h.blocked = False  # resources already released at block time
        else:
            self.scheduler.return_resources(h.node_id, h.lease_spec)
        h.leased_to = None
        h.lease_spec = None
        raylet.release_worker(h)

    # ----- blocked-worker resource release (reference: the raylet's
    # NotifyDirectCallTaskBlocked/Unblocked handling — a worker blocked in
    # get() yields its cpu so dependency producers can schedule; unblock
    # re-acquires, possibly oversubscribing until something finishes;
    # local_task_manager.cc ReleaseCpuResourcesFromBlockedWorker) -----
    def on_worker_blocked(self, worker_id: WorkerID):
        with self._lock:
            raylet, h = self._find_worker(worker_id)
            if h is None or h.blocked or h.actor_id is not None:
                return
            spec = h.current_task if h.current_task is not None \
                else h.lease_spec
            if spec is None:
                return
            h.blocked = True
            self.scheduler.return_resources(h.node_id, spec)
            self._drain_pending()
            raylet.try_dispatch()

    def on_worker_unblocked(self, worker_id: WorkerID):
        with self._lock:
            _, h = self._find_worker(worker_id)
            if h is None or not h.blocked:
                return
            h.blocked = False
            spec = h.current_task if h.current_task is not None \
                else h.lease_spec
            if spec is not None:
                self.scheduler.reacquire(h.node_id, spec)

    def req_return_lease(self, payload, reply, caller):
        wid = WorkerID(payload["worker_id"])
        with self._lock:
            raylet, h = self._find_worker(wid)
            if h is not None:
                self._release_lease_locked(raylet, h)
            self._drain_pending()
            self._drive_pending_pgs()
        reply(True)

    def req_actor_direct_addr(self, payload, reply, caller):
        """Resolve an actor to its worker's direct address, deferring while
        the actor is pending/restarting (reference: the actor table
        subscription that feeds direct_actor_task_submitter.h)."""
        actor_id: ActorID = payload["actor_id"]

        def send_addr(_val=None, error=None):
            if error is not None:
                reply(None, error=error)
                return
            with self._lock:
                info = self.gcs.get_actor_info(actor_id)
                if info is None or info.worker_id is None:
                    reply(error=exc.ActorDiedError("actor is gone"))
                    return
                _, h = self._find_worker(info.worker_id)
                if h is None or h.direct_addr is None:
                    reply(None)  # not direct-capable: classic path
                    return
                reply({"worker_id": info.worker_id.binary(),
                       "addr": h.direct_addr})

        with self._lock:
            info = self.gcs.get_actor_info(actor_id)
            if info is None:
                reply(error=ValueError(f"unknown actor {actor_id}"))
                return
            if info.state == ActorState.DEAD:
                reply(error=exc.ActorDiedError(
                    info.death_cause or "actor dead"))
                return
            if info.state == ActorState.ALIVE:
                pass  # fall through to send_addr below
            else:
                self._actor_waiters[actor_id].append(send_addr)
                return
        send_addr()

    def req_kill_worker(self, payload, reply, caller):
        """Coarse cancel of a direct task: kill its leased worker (classic
        cancel semantics — force=True kills the executing process)."""
        wid = WorkerID(payload["worker_id"])
        with self._lock:
            _, h = self._find_worker(wid)
            if h is not None:
                try:
                    h.proc.kill()
                except Exception:
                    pass
        reply(True)

    # ================= task manager =================
    def submit_task(self, spec: TaskSpec):
        from ray_tpu._private.chaos import maybe_delay

        maybe_delay("submit")
        with self._lock:
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, TaskStatus.PENDING,
                attempt=spec.attempt, type=spec.task_type.name,
                parent_task_id=spec.parent_task_id,
                trace_id=spec.trace_ctx[0] if spec.trace_ctx else None))
            if spec.task_type != TaskType.ACTOR_CREATION:
                self.gcs.record_lineage(spec)
            # Pin arg refs for the task's lifetime (owner-side arg pinning,
            # reference: dependency_manager.h).
            for arg in list(spec.args) + list(spec.kwargs.values()):
                for oid in ([arg.ref] if arg.ref is not None else []) + arg.contained:
                    self.gcs.add_reference(oid, b"task:" + spec.task_id.binary())
            self._schedule(spec)

    def _schedule(self, spec: TaskSpec):
        if self._park_if_unready(spec):
            return
        locality, arg_bytes = self._arg_locality(spec)
        try:
            node_id = self.scheduler.pick_node(spec, locality=locality)
        except Infeasible as e:
            self._fail_task(spec, exc.PlacementGroupSchedulingError(str(e))
                            if spec.scheduling_strategy.kind == "PLACEMENT_GROUP"
                            else exc.RayTpuError(str(e)))
            return
        if node_id is None:
            self.pending.append(spec)
            return
        self._note_locality_placement(spec, node_id, arg_bytes)
        raylet = self.raylets[node_id]
        self.gcs.update_task_status(spec.task_id, TaskStatus.SCHEDULED,
                                    node_id=node_id)
        raylet.queue_task(spec)

    # ---------- arg-locality plane ----------
    @staticmethod
    def _iter_arg_refs(spec: TaskSpec, direct_only: bool = False):
        """Directory-tracked ObjectRef args of a task, deduplicated.
        Owner-resident refs (arg.owner set) resolve worker→owner and are
        invisible to the directory — skipped.  Contained refs (nested in
        arg values, materialized lazily inside the task) count for
        locality scoring but never gate dispatch (direct_only)."""
        seen = set()
        for arg in list(spec.args) + list(spec.kwargs.values()):
            refs = [arg.ref] if arg.ref is not None and arg.owner is None \
                else []
            if not direct_only:
                refs += list(arg.contained)
            for oid in refs:
                if oid not in seen:
                    seen.add(oid)
                    yield oid

    def _park_if_unready(self, spec: TaskSpec) -> bool:
        """Locality gate: hold a task whose directly-passed ref args don't
        exist anywhere yet (no value, no holder, no spill record) until
        they seal — placement then sees real byte locations instead of
        racing the producer (reference: the raylet's dependency manager
        dispatches tasks only once args are ready, dependency_manager.h).
        Lost args trigger reconstruction; unrecoverable ones get a typed
        error value so the task still dispatches and fails loudly.
        Returns True when the task was parked (re-scheduled from
        _notify_object when the first missing arg becomes available)."""
        if not self._locality_on:
            return False
        for oid in self._iter_arg_refs(spec, direct_only=True):
            entry = self.gcs.object_lookup(oid)
            if entry is not None and entry.lost:
                if not self._try_reconstruct(oid, entry):
                    self._fail_object_locked(oid, exc.ObjectLostError(
                        f"task arg {oid} was lost and cannot be "
                        f"reconstructed"))
                entry = self.gcs.object_lookup(oid)
            if entry is not None and (entry.inline is not None
                                      or entry.locations
                                      or entry.spill is not None):
                continue  # a value, a holder, or a restorable copy exists
            self._dep_parked[oid].append(spec)
            return True
        return False

    def _arg_locality(self, spec: TaskSpec):
        """(locality, arg_bytes) for a task's ref args: ``locality`` maps
        node -> resident arg bytes on that node's HOST (any node on the
        holder's host reads via zero-copy segment attach, so the signal
        is host-level); ``arg_bytes`` lists (oid, size, hosts, entry)
        per sized directory arg, reused for hit/miss metrics and
        prefetch targeting after placement."""
        if not self._locality_on:
            return None, []
        arg_bytes = []
        host_bytes: Dict[str, float] = {}
        for oid in self._iter_arg_refs(spec):
            entry = self.gcs.object_lookup(oid)
            if entry is None or entry.inline is not None \
                    or not entry.locations or not entry.size:
                continue
            hosts = {self.node_host.get(nid, self.host_key)
                     for nid in entry.locations}
            arg_bytes.append((oid, entry.size, hosts, entry))
            for hk in hosts:
                host_bytes[hk] = host_bytes.get(hk, 0.0) + entry.size
        if not host_bytes:
            return None, arg_bytes
        locality = {nid: host_bytes[hk]
                    for nid, hk in self.node_host.items()
                    if host_bytes.get(hk)}
        return (locality or None), arg_bytes

    def _note_locality_placement(self, spec: TaskSpec, node_id: NodeID,
                                 arg_bytes) -> None:
        """Post-placement accounting + prefetch kick: count how many arg
        bytes the chosen host already holds, and start pulling the rest
        into the chosen node's store while the task is still queued."""
        if not self._locality_on or not arg_bytes:
            return
        chosen_host = self.node_host.get(node_id, self.host_key)
        local = remote = 0.0
        missing = []
        for oid, size, hosts, entry in arg_bytes:
            if chosen_host in hosts:
                local += size
            else:
                remote += size
                missing.append((oid, size, entry))
        self._loc_counter_add("sched_locality_tasks_total", 1)
        self._loc_counter_add("sched_locality_hits_total"
                              if not missing else
                              "sched_locality_misses_total", 1)
        if local:
            self._loc_counter_add("sched_locality_local_arg_bytes_total",
                                  local)
            if self._has_remote:
                # Bytes that stayed off the wire because placement
                # followed them (only meaningful once a wire exists).
                self._loc_counter_add(
                    "sched_locality_transfer_bytes_avoided_total", local)
        if remote:
            self._loc_counter_add("sched_locality_remote_arg_bytes_total",
                                  remote)
        tot_l = self._loc_counters.get(
            "sched_locality_local_arg_bytes_total", 0.0)
        tot_r = self._loc_counters.get(
            "sched_locality_remote_arg_bytes_total", 0.0)
        if tot_l + tot_r > 0:
            self._loc_gauge_set("sched_locality_local_bytes_fraction",
                                tot_l / (tot_l + tot_r))
        if missing and self._locality_prefetch:
            for oid, size, entry in missing:
                self._start_prefetch(spec, oid, size, entry, node_id,
                                     chosen_host)

    def _loc_counter_add(self, name: str, delta: float) -> None:
        """Bump a sched_locality_* counter; write-through to the GCS KV
        metrics namespace so /metrics (util.metrics.prometheus_text)
        exports it.  In-process dict + pickle — cheap per placement."""
        val = self._loc_counters.get(name, 0.0) + delta
        self._loc_counters[name] = val
        try:
            self.gcs.kv_put((name + "|").encode(), pickle.dumps(val),
                            namespace="metrics")
        except Exception:
            pass

    def _loc_gauge_set(self, name: str, value: float) -> None:
        self._loc_counters[name] = value
        try:
            self.gcs.kv_put((name + "|").encode(), pickle.dumps(value),
                            namespace="metrics")
        except Exception:
            pass

    def locality_stats(self) -> dict:
        """Locality-plane counters + the recent prefetch wall-stamp log
        (smoke/bench proof surface; counters mirror /metrics)."""
        with self._lock:
            return {"counters": dict(self._loc_counters),
                    "prefetch": [dict(r) for r in self._prefetch_log]}

    def _note_pull_resolution(self, resolved: Optional[dict]) -> None:
        """A cross-host "pull" resolution handed to a real caller == that
        many bytes about to cross the transfer plane on demand.  Counted
        ONLY at the resolution-handout sites (req_resolve_batch /
        req_get_locations) — _notify_object's availability probe also
        calls _resolve_object and must not double-count."""
        if resolved is not None and resolved.get("kind") == "pull":
            self._loc_counter_add("sched_locality_wire_bytes_total",
                                  resolved.get("size") or 0)
            self._loc_counter_add("sched_locality_pull_resolutions_total", 1)

    def _start_prefetch(self, spec: TaskSpec, oid: ObjectID, size: int,
                        entry, node_id: NodeID, chosen_host: str) -> None:
        """Pull a missing arg into the chosen node's store while its task
        is still queued (worker spawn / dispatch overlaps the wire).
        Rides the durability plane's store-to-store machinery: replica
        segments are uniquely named, so a racing demand pull by the
        worker can never collide.  Under the head lock."""
        key = (oid, node_id)
        if key in self._prefetch_inflight:
            return
        addrs = []
        for nid in entry.locations:
            if self.node_host.get(nid, self.host_key) == chosen_host:
                return  # already resident on the target host
            addr = self.node_xfer.get(nid)
            if addr is not None:
                addrs.append(tuple(addr))
        raylet = self.raylets.get(node_id)
        if not addrs or raylet is None:
            return  # no pullable holder: the worker's demand path covers it
        self._prefetch_inflight.add(key)
        rec = {"oid": oid.hex(), "node": node_id.hex(),
               "task": spec.task_id.hex(), "bytes": size,
               "start": time.time(), "done": None, "ok": None}
        self._prefetch_recs[key] = rec
        self._prefetch_log.append(rec)
        self._loc_counter_add("sched_locality_prefetch_started_total", 1)
        if isinstance(raylet, RemoteRaylet):
            # The agent pulls into its own store and acks with
            # object_replicated (the durability wire protocol), which
            # registers the location and completes the record.  Partial
            # holders ride along so the agent stripes a big prefetch
            # across every source instead of one stream off addrs[0].
            msg = {"type": "store_pull", "oid": oid.binary(),
                   "addr": list(addrs[0]),
                   "addrs": [list(a) for a in addrs],
                   "size": size, "meta": entry.meta}
            psources, pchunk, _ = self._partial_sources_locked(
                entry, chosen_host)
            if psources:
                seen = {tuple(a) for a in addrs}
                msg["sources"] = [[list(a), None] for a in addrs] + [
                    s for s in psources if tuple(s[0]) not in seen]
                msg["chunk"] = pchunk
            raylet.send_agent(msg)
        else:
            if self._prefetch_q is None:
                import queue as _queue

                self._prefetch_q = _queue.Queue()
                threading.Thread(target=self._prefetch_loop,
                                 name="rtpu-prefetch", daemon=True).start()
            self._prefetch_q.put((oid, node_id, addrs, size))

    _PREFETCH_ATTEMPTS = 5  # seal→store_adopt race on the source agent

    def _prefetch_loop(self):
        """Head-side prefetch worker: store-to-store pulls into local
        (in-head) raylet stores.  Failures are silent — the worker's
        demand pull at materialization time is the correctness path."""
        import time as _time

        while not self._shutdown:
            item = self._prefetch_q.get()
            if item is None:
                return
            oid, node_id, addrs, size = item
            meta = data = None
            for attempt in range(self._PREFETCH_ATTEMPTS):
                for addr in addrs:
                    try:
                        meta, data = self._repl_pull(addr, oid)
                        break
                    except Exception:
                        meta = data = None
                if data is not None or self._shutdown:
                    break
                _time.sleep(0.05 * (2 ** attempt))
            ok = False
            if data is not None:
                with self._lock:
                    raylet = self.raylets.get(node_id)
                    entry = self.gcs.object_lookup(oid)
                    if raylet is not None and entry is not None \
                            and entry.inline is None and not entry.lost:
                        try:
                            seg = raylet.store.put_replica(oid, meta, data)
                            self.gcs.object_sealed(oid, node_id, len(data),
                                                   meta=meta, segment=seg)
                            ok = True
                        except Exception:
                            traceback.print_exc()
                    if ok:
                        self._notify_object(oid)
            self._finish_prefetch((oid, node_id),
                                  len(data) if data is not None else size, ok)

    def _finish_prefetch(self, key: tuple, nbytes: int, ok: bool) -> None:
        with self._lock:
            self._prefetch_inflight.discard(key)
            rec = self._prefetch_recs.pop(key, None)
            if rec is None:
                return
            rec["done"] = time.time()
            rec["ok"] = bool(ok)
            if ok:
                self._loc_counter_add("sched_locality_prefetch_done_total", 1)
                self._loc_counter_add("sched_locality_prefetch_bytes_total",
                                      nbytes)
                self._loc_counter_add(
                    "sched_locality_prefetch_overlap_seconds_total",
                    max(0.0, rec["done"] - rec["start"]))

    def submit_actor_task(self, spec: TaskSpec,
                          dead_worker: Optional[bytes] = None):
        """Route an actor task to the actor's dedicated worker, or queue it
        while the actor is pending/restarting (reference: direct actor task
        submitter's per-actor ordered queue,
        transport/direct_actor_task_submitter.h:67).

        ``dead_worker`` marks a budget-exhausted call rerouted off a dead
        direct channel: it may only land on the SAME incarnation (whose
        death processing will then fail it authoritatively).  If the
        actor has restarted — or is restarting — the call belongs to the
        dead incarnation and must fail, never re-execute: replaying a
        call the caller has no retry budget for onto a fresh incarnation
        re-runs side effects (and a poison call would kill every restart
        until the actor goes DEAD)."""
        with self._lock:
            info = self.gcs.get_actor_info(spec.actor_id)
            if info is None:
                self._fail_task(spec, exc.ActorDiedError("unknown actor"))
                return
            if info.state == ActorState.DEAD:
                self._fail_task(spec, exc.ActorDiedError(
                    info.death_cause or "actor is dead"))
                return
            if dead_worker is not None:
                cur = (info.worker_id.binary()
                       if info.worker_id is not None else None)
                if info.state != ActorState.ALIVE or cur != dead_worker:
                    self._fail_task(spec, exc.ActorDiedError(
                        info.death_cause or "actor worker died"))
                    return
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, TaskStatus.PENDING,
                type="ACTOR_TASK", parent_task_id=spec.parent_task_id,
                trace_id=spec.trace_ctx[0] if spec.trace_ctx else None))
            if info.state != ActorState.ALIVE or info.worker_id is None:
                info.pending_calls.append(spec)
                return
            self._push_actor_task(info, spec)

    def _push_actor_task(self, info, spec: TaskSpec):
        conn = self._conns.get(info.worker_id)
        if conn is None:
            info.pending_calls.append(spec)
            return
        self.running[spec.task_id] = (spec, info.worker_id)
        self.gcs.update_task_status(spec.task_id, TaskStatus.RUNNING,
                                    worker_id=info.worker_id)
        if not self._send_on(conn, {"type": "execute", "spec": spec}):
            # Send failed: this worker's conn is breaking.  Run death
            # processing NOW (idempotent; the lock is reentrant) so the
            # spec left in `running` is adopted and the actor FSM decides
            # replay-vs-fail by retry budget — a writer-only failure must
            # not strand the call, and requeueing to pending_calls here
            # would bypass the budget and re-execute the call on the NEXT
            # incarnation (a poison call — e.g. one that os._exit()s the
            # worker — would then kill every restart until the actor went
            # DEAD).
            self.on_conn_closed(info.worker_id)

    def on_task_done(self, msg: dict):
        from ray_tpu._private.chaos import maybe_delay

        maybe_delay("task_done")
        task_id = TaskID(msg["task_id"])
        with self._lock:
            spec_worker = self.running.pop(task_id, None)
            # Completion can race an OOM kill decision (the monitor marked the
            # task just as its result message arrived) — drop the mark so the
            # map can't grow unboundedly.
            self._oom_killed.pop(task_id, None)
            worker_id = WorkerID(msg["worker_id"])
            raylet, handle = self._find_worker(worker_id)
            spec: Optional[TaskSpec] = msg.get("spec") or (
                spec_worker[0] if spec_worker else None)
            if handle is not None and spec is not None \
                    and spec.task_type == TaskType.NORMAL:
                if handle.blocked:
                    handle.blocked = False  # released at block time
                else:
                    self.scheduler.return_resources(handle.node_id, spec)
            error = msg.get("error")  # (meta, data) serialized exception or None
            results: List[TaskResult] = msg.get("results") or []
            if spec is not None:
                if error is not None and self._maybe_retry(spec, msg):
                    if handle is not None:
                        raylet.release_worker(handle)
                    self._drain_pending()
                    return
                status = TaskStatus.FAILED if error else TaskStatus.FINISHED
                kw = dict(error=msg.get("error_str"), worker_id=worker_id,
                          start=msg.get("start"), end=msg.get("end"))
                if handle is not None:
                    # Keep the SCHEDULED-time node when the worker is
                    # already gone — don't clobber it with None.
                    kw["node_id"] = handle.node_id
                self.gcs.update_task_status(task_id, status, **kw)
                # Unpin arg refs (direct and nested).
                for arg in list(spec.args) + list(spec.kwargs.values()):
                    for oid in ([arg.ref] if arg.ref is not None else []) \
                            + arg.contained:
                        if self.gcs.remove_reference(
                                oid, b"task:" + spec.task_id.binary()):
                            self._free_object(oid)
            node_id = handle.node_id if handle else None
            for res in results:
                self._record_result(res, node_id, task_id, error)
            if error is not None and spec is not None:
                for oid in spec.return_ids():
                    if not any(r.object_id == oid for r in results):
                        self._record_error_result(oid, error)
            # Actor lifecycle notifications.
            if spec is not None and spec.task_type == TaskType.ACTOR_CREATION:
                self._on_actor_creation_done(spec, worker_id, error, msg)
            if handle is not None:
                if spec is not None and spec.task_type == TaskType.ACTOR_TASK:
                    handle.busy = False  # actor workers aren't pooled
                else:
                    raylet.release_worker(handle)
            self._drain_pending()
            self._drive_pending_pgs()

    def _record_result(self, res: TaskResult, node_id, task_id: TaskID,
                       error):
        if res.inline is not None:
            self.gcs.object_inline(res.object_id, res.inline[0], res.inline[1],
                                   lineage_task=task_id)
            if res.contained:
                # Head-counted refs nested in the result value: pin them
                # under the result entry's lifetime.  This runs while
                # processing task_done, which the returner's connection
                # ordered BEFORE its own ref-gc drops — so the nested
                # object cannot be freed in the caller-registration
                # window.  (Owner-resident items carry an owner address
                # and are handled by the direct handover instead.)
                self._link_contained(res.object_id, [
                    c[0] for c in res.contained if c[1] is None])
        elif res.in_store and node_id is not None:
            self.gcs.object_sealed(res.object_id, node_id, res.size,
                                   lineage_task=task_id, meta=res.meta)
        self._notify_object(res.object_id)

    def _record_error_result(self, oid: ObjectID, error):
        self.gcs.object_inline(oid, ERROR_META + error[0], error[1])
        self._notify_object(oid)

    def _maybe_retry(self, spec: TaskSpec, msg: dict) -> bool:
        if spec.task_type == TaskType.ACTOR_TASK:
            # App-level exception on a live actor: retry only when asked
            # (retry_exceptions) and within the method's retry budget
            # (worker-death replay is handled by the actor FSM instead).
            if not spec.retry_exceptions or spec.attempt >= spec.max_retries:
                return False
            spec.attempt += 1
            self.submit_actor_task(spec)
            return True
        crashed = msg.get("crashed", False)
        if not crashed and not spec.retry_exceptions:
            return False
        if spec.attempt >= spec.max_retries:
            return False
        spec.attempt += 1
        self._schedule(spec)
        return True

    def _fail_task(self, spec: TaskSpec, error: BaseException):
        meta, data = _serialize_error(error)
        for oid in spec.return_ids():
            self._record_error_result(oid, (meta, data))
        self.gcs.update_task_status(spec.task_id, TaskStatus.FAILED,
                                    error=str(error))
        # Lost puts waiting on this task's re-execution (put
        # reconstruction, _try_reconstruct) can never recover now: fail
        # them typed so their waiters error instead of hanging.
        for oid, e in list(self.gcs.objects.items()):
            if e.lost and oid.is_put() and oid.task_id() == spec.task_id:
                self._fail_object_locked(oid, exc.ObjectLostError(
                    f"put {oid} was lost and its creating task could "
                    f"not be re-executed: {error}"))
        if spec.task_type == TaskType.ACTOR_CREATION:
            info = self.gcs.get_actor_info(spec.actor_id)
            if info is not None:
                self.gcs.kill_actor(spec.actor_id)
                info.death_cause = str(error)
                self._notify_actor_waiters(spec.actor_id, error=error)
                self._fail_pending_actor_calls(info, error)

    def cancel_task(self, task_id: TaskID):
        with self._lock:
            # Parked on a not-yet-produced arg (locality gate).
            for oid, lst in list(self._dep_parked.items()):
                for spec in list(lst):
                    if spec.task_id == task_id:
                        lst.remove(spec)
                        if not lst:
                            self._dep_parked.pop(oid, None)
                        self._fail_task(spec,
                                        exc.RayTpuError("task cancelled"))
                        return
            for q in [self.pending] + [r.queued for r in self.raylets.values()]:
                for spec in list(q):
                    if spec.task_id == task_id:
                        q.remove(spec)
                        self._fail_task(spec, exc.RayTpuError("task cancelled"))
                        return
            # Running normal tasks: find the worker currently executing it.
            for raylet in self.raylets.values():
                for handle in raylet.workers.values():
                    t = handle.current_task
                    if t is not None and t.task_id == task_id \
                            and handle.actor_id is None:
                        self._cancelled.add(task_id)
                        # Coarse cancel (like force=True in the reference):
                        # kill the worker; death handler fails the task.
                        try:
                            handle.proc.kill()
                        except Exception:
                            pass
                        return

    def _drain_pending(self):
        if not self.pending:
            return
        still: deque = deque()
        # Per-scheduling-class early-out (reference: the raylet queues tasks
        # by SchedulingClass, cluster_task_manager.h): once a class finds no
        # feasible node in this pass, its remaining tasks are skipped — the
        # drain is O(pending) instead of O(pending * completions).  Tasks
        # with placement strategies schedule against per-task state (PG
        # bundle, target node), so only default-strategy tasks share a key.
        blocked: set = set()
        while self.pending:
            spec = self.pending.popleft()
            key = (spec.scheduling_class()
                   if spec.scheduling_strategy.kind == "DEFAULT" else None)
            if key is not None and key in blocked:
                still.append(spec)
                continue
            try:
                locality, arg_bytes = self._arg_locality(spec)
                node_id = self.scheduler.pick_node(spec, locality=locality)
            except Infeasible as e:
                self._fail_task(spec, exc.RayTpuError(str(e)))
                continue
            if node_id is None:
                still.append(spec)
                if key is not None:
                    blocked.add(key)
            else:
                self._note_locality_placement(spec, node_id, arg_bytes)
                self.gcs.update_task_status(spec.task_id, TaskStatus.SCHEDULED,
                                            node_id=node_id)
                self.raylets[node_id].queue_task(spec)
        self.pending = still

    def _drive_pending_pgs(self):
        if not self._pending_pgs:
            return
        still = []
        for pg in self._pending_pgs:
            if self.scheduler.create_placement_group(pg):
                self.gcs.publish("PG", ("CREATED", pg.pg_id))
                for cb in self._pg_waiters.pop(pg.pg_id, []):
                    cb("CREATED")
            else:
                still.append(pg)
        self._pending_pgs = still

    # ================= workers: running-task bookkeeping =================
    def on_task_started(self, task_id, worker_id):
        # Dispatch marks running implicitly; normal tasks record here via raylet.
        pass

    def _find_worker(self, worker_id: WorkerID):
        for raylet in self.raylets.values():
            h = raylet.workers.get(worker_id)
            if h is not None:
                return raylet, h
        return None, None

    def _handle_worker_death(self, handle: WorkerHandle, cause: str):
        self._drop_partials_for(handle.worker_id.binary())
        if handle.leased_to is not None:
            # Leased worker died: return the lease's held resources.  The
            # lessee sees the channel break and handles its own in-flight
            # retries (owner-side task manager, see direct.py).
            if handle.blocked:
                handle.blocked = False  # released at block time
            else:
                self.scheduler.return_resources(handle.node_id,
                                                handle.lease_spec)
            handle.leased_to = None
            handle.lease_spec = None
        spec = handle.current_task
        if spec is not None and spec.task_type == TaskType.ACTOR_CREATION:
            # Died mid-creation: release and let the actor FSM below decide
            # whether to retry (max_restarts) or die.
            self.scheduler.return_resources(handle.node_id, spec)
            self.running.pop(spec.task_id, None)
        elif spec is not None and spec.task_type == TaskType.NORMAL:
            if handle.blocked:
                handle.blocked = False
            else:
                self.scheduler.return_resources(handle.node_id, spec)
            self.running.pop(spec.task_id, None)
            cancelled = spec.task_id in self._cancelled
            oom = self._oom_killed.pop(spec.task_id, None)
            if cancelled:
                self._cancelled.discard(spec.task_id)
                self._fail_task(spec, exc.RayTpuError("task cancelled"))
            elif spec.attempt < spec.max_retries:
                spec.attempt += 1
                self._schedule(spec)
            elif oom is not None:
                self._fail_task(spec, exc.OutOfMemoryError(
                    f"task was killed by the memory monitor under host "
                    f"memory pressure (usage {oom:.0%} at kill time) and "
                    f"exhausted its retries"))
            else:
                self._fail_task(spec, exc.WorkerCrashedError(cause))
        # Collect in-flight actor tasks bound to this worker: the actor FSM
        # decides whether they replay (max_task_retries across a restart,
        # reference: task_manager.h actor-task resubmit) or fail.
        inflight: List[TaskSpec] = []
        for task_id, (tspec, wid) in list(self.running.items()):
            if wid == handle.worker_id:
                self.running.pop(task_id, None)
                if tspec.task_type == TaskType.ACTOR_TASK:
                    inflight.append(tspec)
                else:
                    meta, data = _serialize_error(exc.ActorDiedError(cause))
                    for oid in tspec.return_ids():
                        self._record_error_result(oid, (meta, data))
        if handle.actor_id is not None:
            self._on_actor_worker_death(handle.actor_id, cause, inflight)
        else:
            self._fail_specs(inflight, exc.ActorDiedError(cause))

    def _fail_specs(self, specs, error: BaseException):
        if not specs:
            return
        meta, data = _serialize_error(error)
        for spec in specs:
            for oid in spec.return_ids():
                self._record_error_result(oid, (meta, data))

    # ================= actors =================
    def _on_actor_creation_done(self, spec: TaskSpec, worker_id: WorkerID,
                                error, msg):
        info = self.gcs.get_actor_info(spec.actor_id)
        if info is None:
            return
        if error is None:
            _, handle = self._find_worker(worker_id)
            node_id = handle.node_id if handle else None
            info.resources_held = True  # live actor keeps its creation resources
            self.gcs.actor_started(spec.actor_id, node_id, worker_id)
            self._notify_actor_waiters(spec.actor_id)
            calls, info.pending_calls = info.pending_calls, []
            for call in calls:
                self._push_actor_task(info, call)
        else:
            raylet, handle = self._find_worker(worker_id)
            if handle is not None:
                self.scheduler.return_resources(handle.node_id, spec)
                handle.actor_id = None
                # The worker process holds a half-constructed actor; recycle it.
                try:
                    handle.proc.kill()
                except Exception:
                    pass
            self.gcs.kill_actor(spec.actor_id)
            info.death_cause = msg.get("error_str") or "actor __init__ failed"
            err = exc.ActorDiedError(info.death_cause)
            self._notify_actor_waiters(spec.actor_id, error=err)
            self._fail_pending_actor_calls(info, err)

    def _on_actor_worker_death(self, actor_id: ActorID, cause: str,
                               inflight: Optional[List[TaskSpec]] = None):
        info = self.gcs.get_actor_info(actor_id)
        if info is None:
            self._fail_specs(inflight or [], exc.ActorDiedError(cause))
            return
        creation_spec = info.creation_spec
        if info.resources_held and info.node_id is not None:
            info.resources_held = False
            self.scheduler.return_resources(info.node_id, creation_spec)
        state = self.gcs.actor_failed(actor_id, cause)
        if state == ActorState.RESTARTING:
            # Replay in-flight calls that still have retry budget, AHEAD of
            # queued-but-never-started calls (submission order); the rest
            # fail with the death cause.
            replay, drop = [], []
            for t in (inflight or []):
                if t.attempt < t.max_retries:
                    t.attempt += 1
                    replay.append(t)
                else:
                    drop.append(t)
            info.pending_calls[:0] = replay
            self._fail_specs(drop, exc.ActorDiedError(cause))
            new_spec = creation_spec
            new_spec.attempt += 1
            self._schedule(new_spec)
        else:
            err = exc.ActorDiedError(cause)
            self._fail_specs(inflight or [], err)
            self._notify_actor_waiters(actor_id, error=err)
            self._fail_pending_actor_calls(info, err)

    def _fail_pending_actor_calls(self, info, error: BaseException):
        calls, info.pending_calls = info.pending_calls, []
        meta, data = _serialize_error(error)
        for call in calls:
            for oid in call.return_ids():
                self._record_error_result(oid, (meta, data))

    def _notify_actor_waiters(self, actor_id: ActorID,
                              error: Optional[BaseException] = None):
        for cb in self._actor_waiters.pop(actor_id, []):
            try:
                if error is None:
                    cb(True)
                else:
                    cb(None, error=error)
            except TypeError:
                cb(True)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self._lock:
            info = self.gcs.get_actor_info(actor_id)
            if info is None:
                return
            if no_restart:
                info.max_restarts = 0
            worker_id = info.worker_id
            if info.resources_held and info.node_id is not None:
                info.resources_held = False
                self.scheduler.return_resources(info.node_id, info.creation_spec)
            self.gcs.kill_actor(actor_id)
            err = exc.ActorDiedError("actor killed")
            self._fail_pending_actor_calls(info, err)
            if worker_id is not None:
                _, handle = self._find_worker(worker_id)
                if handle is not None:
                    try:
                        handle.proc.kill()
                    except Exception:
                        pass
            self._drain_pending()

    # ================= objects =================
    def on_seal(self, msg: dict):
        """A worker sealed a large object directly into shm; adopt it."""
        with self._lock:
            self._seal_one_locked(msg)

    def _seal_one_locked(self, msg: dict) -> Optional[ObjectID]:
        oid: ObjectID = ObjectID(msg["oid"])
        node_id = NodeID(msg["node_id"])
        raylet = self.raylets.get(node_id)
        if raylet is not None:
            try:
                # Adopt is a no-op when the object was created in the
                # store directly (the driver's pooled-segment put path).
                raylet.store.adopt(oid, msg["size"], msg["meta"],
                                   segment=msg.get("segment"))
            except Exception:
                traceback.print_exc()
                return None
        self.gcs.object_sealed(oid, node_id, msg["size"],
                               lineage_task=msg.get("lineage_task"),
                               meta=msg.get("meta"),
                               segment=msg.get("segment"))
        self._link_contained(oid, msg.get("contained"))
        self._maybe_make_durable(oid, msg["size"])
        self._notify_object(oid)
        return oid

    def _link_contained(self, oid: ObjectID, contained) -> None:
        """Pin head-counted refs nested in an object's value under the
        object's own lifetime (res:<oid> holders), released cascading in
        _free_object.  Ordering makes this race-free: the seal/result
        message carrying the nested ids rides the creator's connection
        BEFORE its own ref-gc drop, so the nested object can never be
        freed in the handoff window between the creator's drop and the
        consumer's register (reference: reference_count.h:543)."""
        if not contained:
            return
        entry = self.gcs.object_lookup(oid)
        if entry is None:
            return
        holder = b"res:" + oid.binary()
        linked = entry.contained or []
        for coid_bin in contained:
            coid = ObjectID(coid_bin)
            if coid == oid or coid in linked:
                continue  # duplicate seal frame (chaos dup / resend)
            self.gcs.add_reference(coid, holder)
            linked.append(coid)
        entry.contained = linked

    def on_seal_batch(self, msg: dict):
        """Coalesced seal burst (put_many): adopt + register every object
        and its submitter's holder ref under ONE lock acquisition / ONE
        control-plane message, in submission order."""
        holder = msg.get("holder")
        with self._lock:
            for item in msg["items"]:
                oid = self._seal_one_locked(item)
                if oid is not None and holder is not None:
                    self.gcs.add_reference(oid, holder)

    def on_put_inline_batch(self, msg: dict):
        """Coalesced inline-put burst (put_many), applied in order."""
        with self._lock:
            for item in msg["items"]:
                oid = ObjectID(item["oid"])
                self.gcs.object_inline(oid, item["meta"], item["data"],
                                       lineage_task=item.get("lineage_task"))
                self._link_contained(oid, item.get("contained"))
                self._notify_object(oid)

    def on_arena_sealed(self, msg: dict):
        """Driver wrote directly into the head raylet's native arena."""
        oid = ObjectID(msg["oid"])
        with self._lock:
            self.gcs.object_sealed(oid, NodeID(msg["node_id"]), msg["size"],
                                   lineage_task=msg.get("lineage_task"))
            self._link_contained(oid, msg.get("contained"))
            self._maybe_make_durable(oid, msg["size"])
            self._notify_object(oid)

    def on_put_inline(self, msg: dict):
        oid = ObjectID(msg["oid"])
        with self._lock:
            self.gcs.object_inline(oid, msg["meta"], msg["data"],
                                   lineage_task=msg.get("lineage_task"))
            self._link_contained(oid, msg.get("contained"))
            self._notify_object(oid)

    # ----- cooperative broadcast: partial-holder directory -----
    def on_object_partial(self, msg: dict, host: Optional[str]):
        """A receiver mid-pull advertises chunk ranges it has landed; the
        record makes it a stripe source for concurrent pullers (torrent-
        style dissemination).  Dies with its process (death hooks call
        _drop_partials_for) or on the explicit drop notify after seal."""
        oid = ObjectID(msg["oid"])
        key = msg["key"]
        with self._lock:
            entry = self.gcs.object_lookup(oid)
            if entry is None or entry.inline is not None:
                return
            p = entry.partials
            if p is None:
                p = entry.partials = {}
            rec = p.get(key)
            if rec is None:
                rec = p[key] = {"addr": tuple(msg["addr"]),
                                "chunk": int(msg["chunk"]),
                                "total": int(msg["total"]),
                                "chunks": set(),
                                "host": host or self.host_key}
                self._partial_index[key].add(oid)
            rec["chunks"].update(msg.get("chunks") or ())

    def on_object_partial_drop(self, msg: dict):
        oid = ObjectID(msg["oid"])
        key = msg["key"]
        with self._lock:
            entry = self.gcs.object_lookup(oid)
            if entry is not None and entry.partials:
                entry.partials.pop(key, None)
                if not entry.partials:
                    entry.partials = None
            oids = self._partial_index.get(key)
            if oids is not None:
                oids.discard(oid)
                if not oids:
                    self._partial_index.pop(key, None)

    def _drop_partials_for(self, key: bytes) -> None:
        """Clear every partial advertisement a dead process made (under
        the head lock): a vanished peer must not be handed out as a
        stripe source — pullers would burn a range timeout on it."""
        for oid in self._partial_index.pop(key, ()):
            entry = self.gcs.object_lookup(oid)
            if entry is not None and entry.partials:
                entry.partials.pop(key, None)
                if not entry.partials:
                    entry.partials = None

    def _partial_sources_locked(self, entry, exclude_host: str):
        """(sources, chunk) for a pull resolution: every cross-host
        partial holder with at least one landed chunk, uniform chunk
        unit (mixed-config advertisers are skipped — range alignment
        needs one unit).  Also reports whether a SAME-host pull is in
        progress (the segment-coalescing hint for _pull_once)."""
        sources: list = []
        chunk = None
        local = False
        if entry.partials:
            for rec in entry.partials.values():
                if rec["host"] == exclude_host:
                    local = True
                    continue
                if not rec["chunks"]:
                    continue
                if chunk is None:
                    chunk = rec["chunk"]
                elif rec["chunk"] != chunk:
                    continue
                sources.append([list(rec["addr"]),
                                sorted(rec["chunks"])])
                if len(sources) >= 16:
                    break
        return sources, chunk, local

    def _caller_host(self, caller: Optional[WorkerID]) -> str:
        """Host key of the process asking for an object."""
        if caller is None:
            return self.host_key
        hk = self._driver_hosts.get(caller.binary())
        if hk is not None:
            return hk
        _, handle = self._find_worker(caller)
        if handle is not None:
            return self.node_host.get(handle.node_id, self.host_key)
        return self.host_key

    def _resolve_object(self, oid: ObjectID, peek: bool = False,
                        caller_host: Optional[str] = None) -> Optional[dict]:
        """Returns a resolution message or None if not yet available.

        Host-aware: a caller on the same host as a location attaches the
        shm segment (zero-copy); a caller on a different host gets a "pull"
        resolution naming the owning store's transfer server (the
        reference's ownership-based directory + pull manager,
        ownership_based_object_directory.h, pull_manager.h:52)."""
        entry = self.gcs.object_lookup(oid)
        if entry is None:
            return None
        if entry.inline is not None:
            meta, data = entry.inline
            if meta.startswith(ERROR_META):
                return {"kind": "error", "meta": meta[len(ERROR_META):], "data": data}
            return {"kind": "inline", "meta": meta, "data": data}
        ch = caller_host or self.host_key
        local_misses = 0
        # Same-host locations first: direct segment attach.
        for node_id in entry.locations:
            if self.node_host.get(node_id, self.host_key) != ch:
                continue
            raylet = self.raylets.get(node_id)
            if raylet is None:
                continue
            if isinstance(raylet.store, RemoteStoreProxy):
                # The store lives in the caller's host's agent/driver
                # process.  A spill record means the segment is gone and
                # the bytes live in the agent's spill file; otherwise the
                # segment is attachable by name on that host.
                hit = raylet.store.spilled_lookup(oid)
                if hit is not None:
                    return hit
                if entry.meta is not None:
                    return {"kind": "store", "oid": oid, "meta": entry.meta,
                            "segment": entry.segments.get(node_id)}
            else:
                hit = raylet.store.arena_lookup(oid)
                if hit is not None:
                    return hit
                meta = raylet.store.meta(oid)
                if meta is not None:
                    return {"kind": "store", "oid": oid, "meta": meta,
                            "segment": raylet.store.segment_of(oid)}
                hit = raylet.store.spilled_lookup(oid)
                if hit is not None:
                    return hit
                local_misses += 1
        # Cross-host: hand out a pull resolution against the owning
        # stores.  ALL live holder addresses ride along so the puller can
        # fail over to an alternate replica when the serving node dies
        # mid-pull (location failover, reference: pull_manager retries
        # against updated object directory locations).
        addrs = []
        for node_id in entry.locations:
            if self.node_host.get(node_id, self.host_key) == ch:
                continue
            addr = self.node_xfer.get(node_id)
            if addr is not None:
                addrs.append(list(addr))
        if addrs:
            out = {"kind": "pull", "oid": oid, "addr": addrs[0],
                   "addrs": addrs, "size": entry.size}
            # Serialization meta rides along so a striped pull can seal
            # even when every byte came from meta-less partial holders.
            meta = entry.meta
            if meta is None:
                for node_id in entry.locations:
                    raylet = self.raylets.get(node_id)
                    if raylet is not None and not isinstance(
                            raylet.store, RemoteStoreProxy):
                        m = raylet.store.meta(oid)
                        if m is not None:
                            meta = m
                            break
            if meta is not None:
                out["meta"] = meta
            psources, pchunk, local = self._partial_sources_locked(entry, ch)
            if psources:
                seen = {tuple(a) for a in addrs}
                out["sources"] = [[a, None] for a in addrs] + [
                    s for s in psources if tuple(s[0]) not in seen]
                out["chunk"] = pchunk
            if local:
                # Someone on the caller's host is mid-pull on this very
                # object: the caller should wait for that seal instead
                # of racing the canonical segment create.
                out["local_partial"] = True
            return out
        # Directory-side spill record readable on the caller's host: the
        # owning store (node) is gone but its file survives.
        if entry.spill is not None \
                and (entry.spill_host or self.host_key) == ch:
            path, meta, size = entry.spill
            return {"kind": "spilled", "path": path, "meta": meta,
                    "size": size}
        if entry.locations and local_misses == len(entry.locations):
            # Every location was a local store that no longer has the bytes.
            entry.locations.clear()
            entry.segments.clear()
            entry.lost = True
        return None

    def _notify_object(self, oid: ObjectID):
        if self._resolve_object(oid) is None:
            return
        # Tasks parked on this arg (locality gate): schedule them now
        # that the directory knows where the bytes live — remaining
        # missing args just re-park on their own oid.
        parked = self._dep_parked.pop(oid, None)
        if parked:
            for spec in parked:
                self._schedule(spec)
        # Callbacks re-resolve per caller host (cross-host waiters need a
        # pull resolution, same-host waiters a segment attach).
        for cb in self._object_waiters.pop(oid, []):
            try:
                cb(oid)
            except Exception:
                pass

    def _object_is_referenced(self, oid: ObjectID) -> bool:
        entry = self.gcs.object_lookup(oid)
        return entry is not None and bool(entry.holders)

    def _on_object_evicted(self, oid: ObjectID, node_id: NodeID):
        entry = self.gcs.object_lookup(oid)
        if entry is not None:
            entry.locations.discard(node_id)
            entry.segments.pop(node_id, None)
            if not entry.locations and entry.inline is None:
                entry.lost = True

    def _try_reconstruct(self, oid: ObjectID, entry) -> bool:
        """Recovery for an object with no readable copy: lineage
        reconstruction first (reference: object_recovery_manager.h:41),
        then the durability plane's spill/backup record.

        Puts reconstruct too, when made INSIDE a task: a put id embeds
        its creating task id, so while that task's lineage is retained
        (its returns are still referenced) a deterministic re-execution
        re-seals the same put ids — this closes the async-durability
        window where a node dies between a put's seal and its replica
        landing.  Driver puts and actor-task puts have no retained
        lineage and fall through to the spill record."""
        from ray_tpu._private.recovery import note

        task = self.gcs.get_lineage(oid.task_id())
        if task is not None and not oid.is_put():
            task.attempt += 1
            entry.lost = False
            note("objects_reconstructed")
            self._schedule(task)
            return True
        # Puts: a spill/backup record restores deterministically without
        # recompute — prefer it over re-running the creating task.
        if self._restore_from_spill(oid, entry):
            return True
        if task is None:
            return False
        ev = self.gcs.task_events.get(task.task_id)
        if ev is not None and ev.status in (
                TaskStatus.PENDING, TaskStatus.SCHEDULED,
                TaskStatus.RUNNING):
            # A live attempt (worker-death retry, or the re-run a
            # sibling put of the same task already triggered) will
            # re-seal this put: don't resubmit again.
            return True
        note("objects_reconstructed")
        task.attempt += 1
        # lost stays True until the re-run re-seals the put
        # (object_sealed clears it); if the re-run can never schedule,
        # _fail_task fails this entry typed.
        self._schedule(task)
        return True

    def _restore_from_spill(self, oid: ObjectID, entry) -> bool:
        """Re-materialize an object from its directory-side spill record
        into a surviving local store, so every caller (any host) resolves
        it again.  Only head-host files are readable here; remote spill
        files are served by their (surviving) agent instead."""
        from ray_tpu._private.recovery import note

        if entry.spill is None:
            return False
        if (entry.spill_host or self.host_key) != self.host_key:
            return False  # the file lives on a host we cannot read
        path, meta, _size = entry.spill
        target_nid = target = None
        for nid, raylet in self.raylets.items():
            if not isinstance(raylet.store, RemoteStoreProxy) \
                    and not raylet.dead:
                target_nid, target = nid, raylet
                break
        if target is None:
            # No live local store to land it in: same-host readers are
            # still served straight off the file (resolution "spilled").
            entry.lost = False
            return True
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        try:
            seg = target.store.put_replica(oid, meta, data)
        except Exception:
            return False
        self.gcs.object_sealed(oid, target_nid, len(data), meta=meta,
                               segment=seg)
        entry.lost = False
        note("objects_restored")
        self._notify_object(oid)
        return True

    def _free_object(self, oid: ObjectID):
        entry = self.gcs.object_lookup(oid)
        if entry is None:
            return
        if b"task:" in {h[:5] for h in entry.holders}:
            return
        if self._arena_leases.get(oid):
            # A reader still holds a zero-copy view over the arena slot:
            # defer the free until the last lease is returned (plasma
            # semantics — never recycle memory under a client).
            self._arena_pending_free.add(oid)
            return
        self._arena_pending_free.discard(oid)
        for node_id in list(entry.locations):
            raylet = self.raylets.get(node_id)
            if raylet is not None:
                raylet.store.delete(oid)
        if entry.partials:
            for key in entry.partials:
                oids = self._partial_index.get(key)
                if oids is not None:
                    oids.discard(oid)
                    if not oids:
                        self._partial_index.pop(key, None)
        contained = entry.contained
        self.gcs.free_object(oid)
        if contained:
            # Cascade: the outer object's death releases its res: pins on
            # nested refs — freeing them too when nothing else holds them.
            holder = b"res:" + oid.binary()
            for coid in contained:
                if self.gcs.remove_reference(coid, holder):
                    self._free_object(coid)

    # ----- arena reader leases -----
    def _grant_arena_lease(self, oid: ObjectID, caller: Optional[WorkerID]):
        holder = caller.binary() if caller is not None else b"driver"
        with self._lock:
            holders = self._arena_leases[oid]
            holders[holder] = holders.get(holder, 0) + 1

    def on_arena_release(self, msg: dict):
        oid = ObjectID(msg["oid"])
        holder = msg["holder"]
        with self._lock:
            holders = self._arena_leases.get(oid)
            if holders is not None and holder in holders:
                if holders[holder] <= 1:
                    holders.pop(holder)
                else:
                    holders[holder] -= 1
                if not holders:
                    self._arena_leases.pop(oid, None)
            self._maybe_complete_deferred_free(oid)

    def _drop_arena_leases_for(self, holder: bytes):
        for oid in list(self._arena_leases.keys()):
            # .get(): a reentrant on_arena_release (GC finalizer on this
            # thread — the RLock does not exclude it) may have removed the
            # entry since the snapshot.
            holders = self._arena_leases.get(oid)
            if holders is not None and holder in holders:
                holders.pop(holder)
                if not holders:
                    self._arena_leases.pop(oid, None)
                self._maybe_complete_deferred_free(oid)

    def _maybe_complete_deferred_free(self, oid: ObjectID):
        if oid in self._arena_pending_free and not self._arena_leases.get(oid):
            self._arena_pending_free.discard(oid)
            self._free_object(oid)

    # ================= object durability =================
    def _maybe_make_durable(self, oid: ObjectID, size: int):
        """Seal-time hook (under the head lock): puts are non-
        reconstructable — queue them for async replication/backup.  One
        predicate when durability is off; never blocks the seal path."""
        if self._durability_q is not None and size >= self._durability_min \
                and oid.is_put():
            # Callers hold self._lock (seal path) — the pending counter is
            # the quiesce gate's truth, bumped before the queue put so the
            # worker's decrement can never race it below zero.
            self._durability_pending += 1
            self._durability_q.put(oid)

    _DURABILITY_ATTEMPTS = 6  # ~3s of exponential backoff, then give up

    def _durability_loop(self):
        import time as _time

        while not self._shutdown:
            item = self._durability_q.get()
            if item is None:
                return
            oid, attempt = item if isinstance(item, tuple) else (item, 0)
            ok = True
            try:
                if self._durability[0] == "replicate":
                    ok = self._replicate_one(oid, self._durability[1])
                else:
                    self._backup_one(oid)
            except Exception:
                traceback.print_exc()
                ok = False
            if ok is False and attempt + 1 < self._DURABILITY_ATTEMPTS \
                    and not self._shutdown:
                # Transient failure — the canonical case: the pull raced
                # the agent's async store_adopt of a freshly-sealed
                # segment, so the source's transfer server doesn't serve
                # the object YET.  Retry with backoff; the pending count
                # is NOT released, so durability_quiesce keeps blocking
                # until the replica truly exists (or attempts exhaust).
                _time.sleep(0.05 * (2 ** attempt))
                self._durability_q.put((oid, attempt + 1))
                continue
            with self._lock:
                self._durability_pending -= 1

    def durability_quiesce(self, timeout: float = 30.0) -> bool:
        """Wait until the async durability worker has replicated/backed up
        every put sealed so far (queue drained AND the in-flight item
        finished).  Chaos tests call this before firing a seeded node
        kill so "the replica exists" is a guarantee, not a race — the
        deterministic-counters contract of the node-loss gates.  Returns
        False on timeout; True immediately when durability is off.
        Best-effort for remote-node replica targets (their store_pull ack
        is asynchronous); copies into head-colocated stores — what the
        tier-1 gates assert on — are synchronous and fully covered."""
        import time as _time

        if self._durability_q is None:
            return True
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                if self._durability_pending <= 0:
                    return True
            _time.sleep(0.01)
        return False

    @staticmethod
    def _read_store_bytes(store) -> "Callable[[ObjectID], tuple]":
        """Reader over a local store covering all three residences a
        sealed object can have: shm segment, native arena, spill file."""
        def read(oid: ObjectID):
            got = store.get(oid)
            if got is not None:
                meta, view = got
                return meta, bytes(view)
            lock = getattr(store, "_lock", None)
            if lock is not None:
                with lock:
                    hit = store.arena_lookup(oid)
                    if hit is not None:
                        from ray_tpu._native import ArenaReader

                        view = ArenaReader.view(hit["store"], hit["offset"],
                                                hit["size"],
                                                hit["capacity"])
                        return hit["meta"], bytes(view)
            rec = store.read_spilled(oid)
            if rec is not None:
                return rec
            return None, None

        return read

    def _replicate_one(self, oid: ObjectID, k: int) -> bool:
        """Bring a put up to K holder locations: copy its bytes into
        surviving stores (direct store-to-store for in-process raylets,
        agent-side pulls for remote nodes).  Async — a node dying
        mid-replication just leaves fewer copies.  Returns False on
        TRANSIENT failures (source not readable yet — e.g. the pull
        raced the agent's async store_adopt — or a target store error)
        so the durability loop retries instead of silently leaving the
        put with no second copy; True when done or permanently moot."""
        from ray_tpu._private.recovery import note

        with self._lock:
            entry = self.gcs.object_lookup(oid)
            if entry is None or entry.inline is not None or entry.lost:
                return True
            have = set(entry.locations)
            need = k - len(have)
            if need <= 0:
                return True
            size = entry.size
            # Source preference: a local store (zero-copy read) over a
            # remote pull.
            src_nid = src_raylet = None
            for nid in have:
                raylet = self.raylets.get(nid)
                if raylet is not None and not isinstance(
                        raylet.store, RemoteStoreProxy):
                    src_nid, src_raylet = nid, raylet
                    break
            src_addr = None
            if src_raylet is None:
                for nid in have:
                    addr = self.node_xfer.get(nid)
                    if addr is not None:
                        src_nid, src_addr = nid, addr
                        break
                if src_addr is None:
                    return False  # no readable source (yet) — retry
            # Targets: local stores first (replicas there survive any
            # agent death and cost no network), then remote agents.
            local_t, remote_t = [], []
            for nid, raylet in self.raylets.items():
                if nid in have or raylet.dead or raylet.max_workers <= 0:
                    continue
                if isinstance(raylet.store, RemoteStoreProxy):
                    remote_t.append((nid, raylet))
                else:
                    local_t.append((nid, raylet))
            if src_raylet is not None:
                src_raylet.store.pin(oid)  # survive eviction mid-copy
        meta = data = None
        try:
            if src_raylet is not None:
                meta, data = self._read_store_bytes(src_raylet.store)(oid)
            else:
                try:
                    meta, data = self._repl_pull(src_addr, oid)
                except Exception:
                    # Usually the seal→store_adopt race on the agent: the
                    # object exists but its store can't serve it yet.
                    return False
        finally:
            if src_raylet is not None:
                src_raylet.store.unpin(oid)
        if data is None:
            return False
        target_errors = 0
        for nid, raylet in local_t:
            if need <= 0:
                break
            try:
                seg = raylet.store.put_replica(oid, meta, data)
            except Exception:
                target_errors += 1
                continue  # store full/racing shutdown: try the next node
            with self._lock:
                if nid not in self.raylets:
                    continue  # died while we copied
                self.gcs.object_sealed(oid, nid, len(data), meta=meta,
                                       segment=seg)
            note("objects_replicated")
            need -= 1
        if need > 0:
            # Remote targets pull from the source's transfer server and
            # ack with "object_replicated" (location registered there).
            pull_addr = self.node_xfer.get(src_nid) if src_addr is None \
                else src_addr
            if pull_addr is None:
                return False
            for nid, raylet in remote_t:
                if need <= 0:
                    break
                raylet.send_agent({"type": "store_pull",
                                   "oid": oid.binary(),
                                   "addr": list(pull_addr),
                                   "size": size, "meta": meta})
                need -= 1
        # Fewer holder nodes than K is a permanent topology fact (best
        # effort, True); an erroring target store is worth another try.
        return not (need > 0 and target_errors > 0)

    def _repl_pull(self, addr, oid: ObjectID):
        if self._repl_client is None:
            from ray_tpu._private.transfer import TransferClient

            self._repl_client = TransferClient(self.authkey)
        return self._repl_client.pull(tuple(addr), oid)

    def _backup_one(self, oid: ObjectID):
        """Durability spill: ensure an on-disk copy exists somewhere (the
        owning store keeps serving from memory; only loss reads the
        file).  The spill callback / object_spilled report mirrors the
        record into the directory, where it survives node death and —
        via the GCS snapshot — head restarts."""
        with self._lock:
            entry = self.gcs.object_lookup(oid)
            if entry is None or entry.inline is not None \
                    or entry.spill is not None:
                return
            target = None
            for nid in entry.locations:
                raylet = self.raylets.get(nid)
                if raylet is None:
                    continue
                if isinstance(raylet.store, RemoteStoreProxy):
                    raylet.send_agent({"type": "store_backup",
                                       "oid": oid.binary()})
                    return
                target = raylet
                break
        if target is not None:
            target.store.backup(oid)  # spill_callback records it

    # ================= shutdown =================
    def shutdown(self):
        self.log_monitor.stop()
        with self._lock:
            self._shutdown = True
            if self._durability_q is not None:
                self._durability_q.put(None)
            if self._prefetch_q is not None:
                self._prefetch_q.put(None)
            if self._repl_client is not None:
                try:
                    self._repl_client.close()
                except Exception:
                    pass
            for raylet in self.raylets.values():
                raylet.shutdown()
            self.raylets.clear()
            for srv in self._local_xfer.values():
                srv.shutdown()
            self._local_xfer.clear()
        for listener in (self._listener, self._tcp_listener):
            try:
                listener.close()
            except Exception:
                pass


def _serialize_error(error: BaseException) -> Tuple[bytes, bytes]:
    s = ser.serialize(error)
    meta, data = ser.pack(s)
    return meta, data
