"""Typed config-flag registry with env-var overrides.

Reference: the RAY_CONFIG x-macro registry (src/ray/common/ray_config_def.h
:17-22, 189 flags, overridable per-process via RAY_<name> env vars and the
_system_config dict passed to ray.init).  Same contract here: every
tunable the runtime consults is DECLARED in one table with a type and
default, overridable via ``RAY_TPU_<NAME>`` env vars or
``ray_tpu.init(_system_config={...})`` — ad-hoc os.environ.get calls are
the anti-pattern this replaces.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, type_: type, default, doc: str):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc

    def parse(self, raw: str):
        if self.type is bool:
            return _parse_bool(raw)
        return self.type(raw)


class RayTpuConfig:
    """Singleton flag table (reference: RayConfig, ray_config.h).

    Resolution order per flag: _system_config override > RAY_TPU_<NAME>
    env var > declared default.  Values are cached after first read;
    ``reset()`` clears the cache (tests)."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._overrides: Dict[str, Any] = {}
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, type_: type, default, doc: str = ""):
        self._flags[name] = _Flag(name, type_, default, doc)
        return self

    def get(self, name: str):
        with self._lock:
            if name in self._cache:
                return self._cache[name]
            flag = self._flags.get(name)
            if flag is None:
                raise KeyError(f"undeclared config flag {name!r}")
            if name in self._overrides:
                ov = self._overrides[name]
                if isinstance(ov, str):
                    # Strings go through the flag parser — bool('0') would
                    # silently flip a disable into an enable.
                    value = flag.parse(ov)
                elif isinstance(ov, flag.type):
                    value = ov
                else:
                    value = flag.type(ov)
            else:
                raw = os.environ.get(_ENV_PREFIX + name.upper())
                value = flag.parse(raw) if raw is not None else flag.default
            self._cache[name] = value
            return value

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def apply_system_config(self, overrides: Optional[Dict[str, Any]]):
        if not overrides:
            return
        with self._lock:
            for k, v in overrides.items():
                if k not in self._flags:
                    raise KeyError(f"unknown _system_config flag {k!r}")
                self._overrides[k] = v
            self._cache.clear()

    def reset(self):
        with self._lock:
            self._overrides.clear()
            self._cache.clear()

    def dump(self) -> Dict[str, Any]:
        """Current value of every declared flag (state API / debugging)."""
        return {name: self.get(name) for name in sorted(self._flags)}

    def doc(self, name: str) -> str:
        return self._flags[name].doc


CONFIG = RayTpuConfig()

# ---- the registry (one declaration per tunable; grep for CONFIG.<name>
# to find the consumer) ----
CONFIG \
    .declare("native_store", bool, False,
             "Use the C++ shared-memory arena for driver puts.  Off by "
             "default: the arena path predates the segment-pool + "
             "batched-notify object plane (put_many coalescing, pooled "
             "pre-faulted segments — the measured 7-8 GB/s path) and "
             "bypasses both; opt in only until it learns those "
             "semantics.  (It was also silently disabled for several "
             "rounds by a stale libshm_store.so built against a newer "
             "glibc — the loader now rebuilds from source instead.)") \
    .declare("worker_idle_ttl_s", float, 300.0,
             "Idle pooled workers are reaped after this long.") \
    .declare("max_workers_per_node", int, 64,
             "Worker-process cap per node.") \
    .declare("health_check_period_s", float, 0.5,
             "Worker liveness poll interval in the head monitor.") \
    .declare("spawn_failure_limit", int, 3,
             "Consecutive worker spawn failures before queued work fails.") \
    .declare("object_store_memory", int, 2 * 1024**3,
             "Default per-node store capacity in bytes.") \
    .declare("inline_object_threshold", int, 100 * 1024,
             "Objects <= this many bytes inline in replies/directory.") \
    .declare("transfer_chunk_bytes", int, 4 * 1024 * 1024,
             "Cross-host object transfer chunk size.") \
    .declare("transfer_pipeline_depth", int, 2,
             "Chunks kept in flight per transfer stream (read-next-"
             "while-sending); 0/1 disables pipelining.") \
    .declare("transfer_stripe_ranges", int, 8,
             "Target number of chunk ranges a striped pull splits an "
             "object into (work-stealing granularity across sources).") \
    .declare("transfer_stripe_min_bytes", int, 8 * 1024 * 1024,
             "Objects at least this large use the striped multi-source "
             "pull path; smaller ones keep the single-stream pull.") \
    .declare("transfer_stripe_sources", int, 4,
             "Max concurrent source streams per striped pull.") \
    .declare("transfer_coop_broadcast", bool, True,
             "Receivers advertise partially-pulled objects as chunk-"
             "range sources (dissemination tree for one-to-N broadcast) "
             "and coalesce concurrent same-object pulls.") \
    .declare("segment_pool", bool, True,
             "Recycle shm segments across puts through size-class free "
             "lists instead of create/unlink per object.") \
    .declare("segment_pool_bytes", int, 0,
             "Free-list byte cap of the segment pool (0 = the store's "
             "capacity).") \
    .declare("segment_pool_prewarm", str, "",
             "Comma list of SIZE:COUNT segments to pre-create and "
             "pre-fault in the background at store startup, e.g. "
             "'64MiB:4,8MiB:8'.") \
    .declare("copy_threads", int, 0,
             "Worker threads for large-buffer memcpy in pack_into "
             "(0 = auto: min(4, cpu//2); 1 = single-threaded).") \
    .declare("parallel_copy_min_bytes", int, 8 * 1024 * 1024,
             "Buffers at least this large are copied by the parallel "
             "memcpy pool.") \
    .declare("spill_enabled", bool, True,
             "Spill referenced objects to disk under memory pressure.") \
    .declare("collective_timeout_s", float, 300.0,
             "Actor-collective rendezvous timeout.") \
    .declare("serve_control_interval_s", float, 1.0,
             "Serve controller reconcile period.") \
    .declare("serve_max_slots", int, 8,
             "LLM engine decode-batch slots per replica (the compiled "
             "decode step's fixed batch dimension).") \
    .declare("serve_page_size", int, 16,
             "Tokens per KV-cache page in the LLM engine's paged pool.") \
    .declare("serve_spec_tokens", int, 0,
             "Speculative-decode window (tokens verified per target "
             "step; >= 2 with a draft model, 0 = plain decode).") \
    .declare("serve_prefill_min_tokens", int, 32,
             "Uncached-tail length at which an admission is offloaded "
             "to a disaggregated prefill replica.") \
    .declare("serve_prefix_cache_bytes", int, 256 * 1024 * 1024,
             "Per-replica host LRU budget for prefix-cache KV pages.") \
    .declare("tcp_host", str, "127.0.0.1",
             "Head TCP bind host (0.0.0.0 to accept remote nodes).") \
    .declare("chaos_delay_us", int, 0,
             "Chaos: max random delay injected at instrumented points.") \
    .declare("scheduler_spread_threshold", float, 0.5,
             "Hybrid policy: node load ratio above which tasks spread.") \
    .declare("task_event_buffer_size", int, 10000,
             "Max task events retained for the state API.") \
    .declare("gcs_snapshot_period_s", float, 0.0,
             "Persist GCS tables every N seconds (0 = disabled).") \
    .declare("tracing_enabled", bool, False,
             "Instrument task submit/execute with OpenTelemetry spans "
             "(API-only; wire a TracerProvider to export).") \
    .declare("tracing_buffer_size", int, 4096,
             "Capacity of the per-process span ring buffer "
             "(drop-oldest; drops counted in "
             "tracing_spans_dropped_total).") \
    .declare("trace_store_max_bytes", int, 32 * 1024 * 1024,
             "Head-side TraceStore global byte budget; whole traces "
             "are evicted LRU past this.") \
    .declare("trace_max_bytes", int, 2 * 1024 * 1024,
             "Per-trace byte budget in the head TraceStore; excess "
             "spans within one trace are dropped and counted.") \
    .declare("flight_record_dir", str, "",
             "Crash flight-recorder bundle directory (also "
             "RAY_TPU_FLIGHT_RECORD_DIR); empty disables postmortem "
             "bundles.") \
    .declare("flight_record_max", int, 16,
             "Max flight-record bundles kept; oldest pruned.") \
    .declare("memory_usage_threshold", float, 0.95,
             "Host/cgroup memory fraction above which the monitor kills "
             "a worker (reference: memory_usage_threshold).") \
    .declare("memory_monitor_refresh_ms", int, 250,
             "Memory-pressure check period (0 disables the monitor; "
             "reference: memory_monitor_refresh_ms).") \
    .declare("worker_killing_policy", str, "retriable_lifo",
             "OOM victim selection: retriable_lifo | group_by_owner "
             "(reference default: ray_config_def.h:103).") \
    .declare("memory_monitor_test_file", str, "",
             "Test hook: read usage fraction from this file instead of "
             "/proc (mirrors the reference's fake-memory test mode).") \
    .declare("node_stats_period_s", float, 2.0,
             "Per-node cpu/mem/store usage snapshot period "
             "(0 disables; reference: the dashboard reporter agent).") \
    .declare("direct_transport", bool, True,
             "Push tasks/actor calls directly to workers over cached "
             "leases, bypassing the head on the hot path (reference: "
             "direct_task_transport.h lease caching).") \
    .declare("lease_idle_s", float, 0.5,
             "Return an idle worker lease to the head after this long.") \
    .declare("reconnect_window_s", float, 30.0,
             "How long agents/workers/drivers retry reconnecting to a "
             "restarted head before giving up (reference: the GCS "
             "reconnect window, ray_config_def.h:58-62).") \
    .declare("rpc_timeout", float, 0.0,
             "Default overall deadline (seconds) for control-plane "
             "requests without an explicit timeout; 0 keeps blocking "
             "semantics unbounded (lost replies still recover via "
             "per-attempt resends).  Env: RAY_TPU_RPC_TIMEOUT.") \
    .declare("rpc_attempt_timeout", float, 15.0,
             "Per-attempt reply wait before a pending request frame is "
             "resent (idempotency keys + the head reply cache make the "
             "resend exactly-once).") \
    .declare("rpc_retry_base_s", float, 0.05,
             "Base backoff between RPC retry attempts (exponential, "
             "jittered, capped at rpc_retry_cap_s).") \
    .declare("rpc_retry_cap_s", float, 2.0,
             "Backoff cap between RPC retry attempts.") \
    .declare("rpc_acked_ops", bool, False,
             "Route one-way notifies/submits through acked, idempotency-"
             "keyed requests so dropped frames are retried (auto-enabled "
             "while RAY_TPU_TESTING_NET_SCHEDULE is set).") \
    .declare("rpc_reply_cache_size", int, 1024,
             "Head-side idempotency reply-cache entries (exactly-once "
             "dedup window for retried/duplicated frames).") \
    .declare("rpc_reply_cache_ttl_s", float, 300.0,
             "Reply-cache entries are evictable this long after their "
             "reply was recorded.") \
    .declare("rpc_hang_dump_s", float, 120.0,
             "The RPC watchdog dumps the blocked thread's stack for any "
             "in-flight call older than this (0 disables dumps).") \
    .declare("rpc_watchdog_interval_s", float, 1.0,
             "Scan period of the per-transport RPC keeper thread "
             "(async resends + hung-call detection).") \
    .declare("transfer_timeout_s", float, 120.0,
             "Per-chunk progress deadline on cross-host object pulls "
             "(0 = wait forever, the pre-deadline behavior).") \
    .declare("transfer_retries", int, 2,
             "Extra pull attempts after a transfer connection failure.") \
    .declare("object_durability", str, "off",
             "Durability policy for non-reconstructable (put) objects: "
             "'off' (hot path untouched), 'replicate:K' (async replicas "
             "on K holder nodes), 'spill' (async backup copy on disk).  "
             "Gives node-loss survivability to objects lineage cannot "
             "rebuild.") \
    .declare("object_durability_min_bytes", int, 0,
             "Only puts at least this large enter the durability plane "
             "(inline puts below inline_object_threshold are head-"
             "resident and already survive node loss).") \
    .declare("node_lease_timeout_s", float, 15.0,
             "A remote node agent whose heartbeat is silent this long is "
             "declared dead (exactly once): its object locations are "
             "discarded, leased/queued work is requeued, and its workers "
             "are struck.  0 disables lease expiry (conn EOF remains the "
             "only death signal).") \
    .declare("node_heartbeat_period_s", float, 1.0,
             "Node-agent liveness heartbeat period (any agent message "
             "also refreshes the lease).") \
    .declare("zero_sharding", str, "off",
             "ZeRO-style data-parallel update sharding for the Train JAX "
             "loops: 'off' | 'opt' (optimizer state sharded 1/N, grads "
             "all-reduced) | 'opt+grads' (grads reduce-scattered too).  "
             "Consumed as the default by the bench GPT-2 loop and "
             "train.jax.compile_zero_step callers; RLlib uses "
             "AlgorithmConfig.resources(zero_sharding=...).") \
    .declare("quantized_collectives", str, "off",
             "Gradient-reduction wire format for the sharded train "
             "steps: 'off' (fp32 psum) | 'int8' (block-scaled int8, "
             "~4x fewer bytes, loss-parity gated).") \
    .declare("locality_scheduling", bool, True,
             "Arg-locality-aware placement: tasks with ObjectRef args "
             "wait for their args to exist, then prefer nodes on the "
             "host already holding the most arg bytes (reference: "
             "locality_aware_lease_policy.h).  'off' restores pure "
             "utilization packing (bench baseline / regression triage).") \
    .declare("locality_min_bytes", int, 1024 * 1024,
             "Resident arg bytes a host must hold before locality "
             "outranks the hybrid utilization score (tiny args are not "
             "worth unbalancing the cluster for).") \
    .declare("locality_prefetch", bool, True,
             "When a task is placed on a node whose host is missing "
             "some of its args, start pulling them into that node's "
             "store while the task is still queued (dispatch overlaps "
             "the wire instead of serializing behind it).")
