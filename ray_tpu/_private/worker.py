"""CoreWorker: per-process runtime — object put/get/wait, task submission,
task execution.  Used by the driver (direct in-process transport to the Head)
and by subprocess workers (socket transport).

Reference equivalents: CoreWorker (src/ray/core_worker/core_worker.h:278),
the in-process memory store (store_provider/memory_store/memory_store.h:43),
the plasma provider (store_provider/plasma_store_provider.h:88) and the
Python-side execute_task loop (python/ray/_raylet.pyx:701).
"""
from __future__ import annotations

import contextlib
import hashlib
import threading
import traceback
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu import object_ref as object_ref_mod
from ray_tpu._private import object_store as store_mod
from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import INLINE_OBJECT_THRESHOLD
from ray_tpu._private.task_spec import (
    ArgKind,
    TaskArg,
    TaskResult,
    TaskSpec,
    TaskType,
)
from ray_tpu.object_ref import ObjectRef


_tracing_mod = None


def _tracing():
    """Lazy tracing-module accessor: imported at first use, not module
    scope (ray_tpu.util imports back into ray_tpu during bootstrap)."""
    global _tracing_mod
    if _tracing_mod is None:
        from ray_tpu.util import tracing as _t

        _tracing_mod = _t
    return _tracing_mod


_obs_mod = None


def _obs():
    """Lazy observability-module accessor (same bootstrap constraint)."""
    global _obs_mod
    if _obs_mod is None:
        from ray_tpu import observability as _o

        _obs_mod = _o
    return _obs_mod


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------
class DirectTransport:
    """Driver-side transport: function calls straight into the Head."""

    def __init__(self, head, worker_id: WorkerID):
        import itertools
        import os as _os

        self.head = head
        self.worker_id = worker_id
        self.authkey = head.authkey
        # Idempotency-key namespace: used only while a net-fault schedule
        # is active (in-process calls cannot be lost otherwise).
        self._key_prefix = _os.urandom(8)
        self._key_counter = itertools.count(1)

    def _net_schedule(self):
        from ray_tpu._private.chaos import net_schedule

        return net_schedule()

    def request(self, op: str, payload: dict, timeout: Optional[float] = None):
        import time as _time

        sched = self._net_schedule()
        if sched is not None:
            return self._request_faulted(sched, op, payload, timeout)
        fut: Future = Future()

        def reply(value=None, error=None):
            if error is not None:
                if not fut.done():
                    fut.set_exception(error)
            elif not fut.done():
                fut.set_result(value)

        start = _time.monotonic()
        self.head.handle_request(op, payload, reply, self.worker_id)
        try:
            # timeout=None keeps blocking semantics (in-process calls
            # cannot lose their reply); a given timeout is enforced.
            return fut.result(timeout=timeout)
        except FuturesTimeoutError:
            raise exc.RpcTimeoutError(
                op=op, elapsed=_time.monotonic() - start, timeout=timeout)

    def _request_faulted(self, sched, op: str, payload: dict,
                         timeout: Optional[float]):
        """Chaos path: the schedule may drop/dup/delay the request or its
        reply, so the call runs a keyed retry loop — resends carry the
        same idempotency key and the head's reply cache applies the op
        exactly once, replaying the recorded reply to late attempts."""
        import time as _time

        from ray_tpu._private import retry as retry_mod
        from ray_tpu._private.chaos import net_request_label

        default_total, attempt_iv = retry_mod.rpc_defaults()
        deadline = retry_mod.Deadline(
            timeout if timeout is not None else default_total)
        key = self._key_prefix + next(self._key_counter).to_bytes(8, "little")
        label = net_request_label(op, payload)
        fut: Future = Future()

        def reply(value=None, error=None):
            act = sched.fault(f"reply:{label}")
            kind = act[0] if act is not None else None
            if kind in ("drop", "sever"):
                return
            if kind == "delay":
                _time.sleep(act[1] / 1000.0)
            if error is not None:
                if not fut.done():
                    fut.set_exception(error)
            elif not fut.done():
                fut.set_result(value)

        attempts = 0
        while True:
            act = sched.fault(f"request:{label}")
            kind = act[0] if act is not None else None
            if kind == "delay":
                _time.sleep(act[1] / 1000.0)
            if kind not in ("drop", "sever"):
                for _ in range(2 if kind == "dup" else 1):
                    self.head.handle_request_keyed(op, payload, reply,
                                                   self.worker_id, key)
            attempts += 1
            try:
                return fut.result(
                    timeout=max(0.001, deadline.bound(attempt_iv)))
            except FuturesTimeoutError:
                pass
            if deadline.expired():
                retry_mod.note("timeouts")
                raise exc.RpcTimeoutError(op=op, elapsed=deadline.elapsed(),
                                          timeout=deadline.timeout,
                                          attempts=attempts)
            retry_mod.note("retries")

    def request_oneway(self, op: str, payload: dict):
        """Fire-and-forget request — the reply (always just an ack on these
        ops) is dropped; errors surface through the task result path.
        Under an active net-fault schedule the op rides the acked, keyed
        request path instead, so a dropped frame is retried and a
        duplicated one applied exactly once."""
        if self._net_schedule() is not None:
            self.request(op, payload)
            return
        self.head.handle_request(op, payload, lambda *a, **k: None,
                                 self.worker_id)

    def notify(self, msg: dict):
        if self._net_schedule() is not None:
            self.request("notify_msg", {"msg": msg})
            return
        t = msg["type"]
        if t == "seal":
            self.head.on_seal(msg)
        elif t == "put_inline":
            self.head.on_put_inline(msg)
        elif t == "seal_batch":
            self.head.on_seal_batch(msg)
        elif t == "put_inline_batch":
            self.head.on_put_inline_batch(msg)
        elif t == "task_done":
            self.head.on_task_done(msg)
        elif t == "arena_sealed":
            self.head.on_arena_sealed(msg)
        elif t == "arena_release":
            self.head.on_arena_release(msg)
        elif t == "object_partial":
            self.head.on_object_partial(msg, self.head.host_key)
        elif t == "object_partial_drop":
            self.head.on_object_partial_drop(msg)

    def store_for(self, node_id):
        """In-process fast path: the driver writes straight into the head
        raylet's store — the native arena when present, pooled shm
        segments otherwise (zero IPC either way)."""
        raylet = self.head.raylets.get(node_id)
        return raylet.store if raylet is not None else None

    def close(self):
        pass


class _Rpc:
    """One logical RPC on a ConnTransport: a single msg_id + idempotency
    key for its whole lifetime — retries resend the *identical* frame, so
    replies to any attempt resolve the same record and the head's reply
    cache applies the op exactly once."""

    __slots__ = ("fut", "op", "frame", "key", "deadline", "started",
                 "last_send", "attempts", "mode", "thread_id", "dumped")

    def __init__(self, fut, op: str, frame: dict, key: bytes, deadline,
                 mode: str):
        import time as _time

        self.fut = fut
        self.op = op
        self.frame = frame
        self.key = key
        self.deadline = deadline
        now = _time.monotonic()
        self.started = now
        self.last_send = now
        self.attempts = 0
        self.mode = mode  # "call" (blocking) | "async" (acked one-way)
        self.thread_id = threading.get_ident()
        self.dumped = False


class ConnTransport:
    """Subprocess-worker transport over a multiprocessing Connection.

    A reader thread (owned by default_worker) routes replies into
    self._pending; sends are serialized by a lock.

    Deadlines + retries: every ``request`` frame carries an idempotency
    key.  A blocking request waits ``rpc_attempt_timeout`` for its reply
    and then resends the same frame (exponentially paced), bounded by the
    caller's timeout (or RAY_TPU_RPC_TIMEOUT when set) — on expiry it
    raises :class:`RpcTimeoutError` instead of blocking forever.  Under
    ``rpc_acked_ops`` (auto-on while a net-fault schedule is active),
    one-way ops (submits, seal/put notifies, task_done) also ride keyed
    request frames; a keeper thread resends the unacked ones, and the
    head's reply cache makes any resend/duplicate exactly-once.  On head
    failover ``replace_conn`` keeps unacked requests registered so they
    are *resent* on the new connection instead of erroring.  The keeper
    doubles as the hung-call watchdog: in-flight ages feed
    retry.rpc_inflight_stats() and calls older than ``rpc_hang_dump_s``
    get their waiting thread's stack dumped to stderr."""

    def __init__(self, conn, authkey: Optional[bytes] = None):
        import os

        from ray_tpu._private import chaos as chaos_mod
        from ray_tpu._private import retry as retry_mod

        self.conn = chaos_mod.wrap_net_faults(conn)
        self.authkey = authkey
        if self.authkey is None:
            hexkey = os.environ.get("RAY_TPU_AUTHKEY")
            self.authkey = bytes.fromhex(hexkey) if hexkey else None
        self._send_lock = threading.Lock()
        self._pending: Dict[int, _Rpc] = {}
        self._msg_counter = 0
        self._futures_lock = threading.Lock()
        self._key_prefix = os.urandom(8)
        self._closed = False
        # Cleared while a reconnect handshake is in flight so resends
        # don't race ahead of re-registration on the fresh conn.
        self._resume_evt = threading.Event()
        self._resume_evt.set()
        self._keeper: Optional[threading.Thread] = None
        retry_mod.register_transport(self)

    # ---- config / chaos accessors ----
    def _acked_ops(self) -> bool:
        from ray_tpu._private.chaos import net_schedule

        if net_schedule() is not None:
            return True
        from ray_tpu._private.config import CONFIG

        return bool(CONFIG.rpc_acked_ops)

    def pending_rpcs(self) -> List[_Rpc]:
        with self._futures_lock:
            return list(self._pending.values())

    def _register(self, op: str, payload: dict, deadline, mode: str) -> _Rpc:
        with self._futures_lock:
            if self._closed:
                raise exc.RayTpuError("connection closed")
            self._msg_counter += 1
            msg_id = self._msg_counter
            key = self._key_prefix + msg_id.to_bytes(8, "little")
            frame = {"type": "request", "msg_id": msg_id, "op": op,
                     "payload": payload, "rpc_key": key}
            if _tracing().tracing_enabled():
                tc = _obs().get_context()
                if tc is not None:
                    frame["tc"] = tc
            rec = _Rpc(Future(), op, frame, key, deadline, mode)
            self._pending[msg_id] = rec
        self._ensure_keeper()
        return rec

    def _deregister(self, rec: _Rpc) -> None:
        with self._futures_lock:
            self._pending.pop(rec.frame["msg_id"], None)

    def request(self, op: str, payload: dict, timeout: Optional[float] = None):
        import time as _time

        from ray_tpu._private import retry as retry_mod

        default_total, attempt_iv = retry_mod.rpc_defaults()
        deadline = retry_mod.Deadline(
            timeout if timeout is not None else default_total)
        rec = self._register(op, payload, deadline, "call")
        fut = rec.fut
        attempt_wait = attempt_iv
        try:
            while True:
                # Held only during a reconnect handshake; set otherwise.
                self._resume_evt.wait(timeout=deadline.bound(attempt_iv))
                try:
                    self.send(rec.frame)
                except (OSError, EOFError, BrokenPipeError):
                    pass  # conn breaking/being replaced: paced retry below
                rec.attempts += 1
                rec.last_send = _time.monotonic()
                try:
                    return fut.result(
                        timeout=max(0.001, deadline.bound(attempt_wait)))
                except FuturesTimeoutError:
                    pass
                if self._closed:
                    raise exc.RayTpuError("connection closed")
                if deadline.expired():
                    retry_mod.note("timeouts")
                    raise exc.RpcTimeoutError(
                        op=op, elapsed=deadline.elapsed(),
                        timeout=deadline.timeout, attempts=rec.attempts)
                retry_mod.note("retries")
                attempt_wait = min(attempt_wait * 1.5, max(attempt_iv, 60.0))
        finally:
            self._deregister(rec)

    def _request_async(self, op: str, payload: dict) -> None:
        """Acked one-way op: one keyed request frame, no blocked thread.
        The keeper thread resends it until the reply lands (or a bounded
        deadline passes); the key makes resends exactly-once."""
        from ray_tpu._private import retry as retry_mod

        default_total, _ = retry_mod.rpc_defaults()
        deadline = retry_mod.Deadline(
            default_total if default_total is not None else 60.0)
        try:
            rec = self._register(op, payload, deadline, "async")
        except exc.RayTpuError:
            return  # closed: matches one-way best-effort semantics
        try:
            self.send(rec.frame)
            rec.attempts += 1
        except (OSError, EOFError, BrokenPipeError):
            pass  # keeper resends

    def on_reply(self, msg: dict):
        with self._futures_lock:
            rec = self._pending.pop(msg["msg_id"], None)
        if rec is None:
            return
        fut = rec.fut
        if fut.done():
            return
        if msg["ok"]:
            fut.set_result(msg["value"])
        else:
            fut.set_exception(msg["error"])

    def notify(self, msg: dict):
        if _tracing().tracing_enabled() and "tc" not in msg:
            tc = _obs().get_context()
            if tc is not None:
                msg["tc"] = tc
        if self._acked_ops():
            self._request_async("notify_msg", {"msg": msg})
        else:
            self.send(msg)

    def request_oneway(self, op: str, payload: dict):
        """Fire-and-forget request: one send, no reply frame, no round
        trip.  Used for acked-only ops on the submission hot path.  In
        acked mode (chaos / rpc_acked_ops) the frame is keyed and
        keeper-retried instead, so a dropped submit cannot strand its
        caller."""
        if self._acked_ops():
            self._request_async(op, payload)
        else:
            frame = {"type": "notify", "op": op, "payload": payload}
            if _tracing().tracing_enabled():
                tc = _obs().get_context()
                if tc is not None:
                    frame["tc"] = tc
            self.send(frame)

    def send(self, msg: dict):
        with self._send_lock:
            self.conn.send(msg)

    # ---- keeper: async resends + hung-call watchdog ----
    def _ensure_keeper(self):
        if self._keeper is not None:
            return
        with self._futures_lock:
            if self._keeper is not None or self._closed:
                return
            t = threading.Thread(target=self._keeper_loop,
                                 name="rtpu-rpc-keeper", daemon=True)
            self._keeper = t
        t.start()

    def _keeper_loop(self):
        import time as _time

        from ray_tpu._private import retry as retry_mod
        from ray_tpu._private.config import CONFIG

        while not self._closed:
            _, attempt_iv = retry_mod.rpc_defaults()
            interval = min(CONFIG.rpc_watchdog_interval_s,
                           max(attempt_iv / 3.0, 0.02))
            _time.sleep(max(0.02, interval))
            hang_s = CONFIG.rpc_hang_dump_s
            now = _time.monotonic()
            with self._futures_lock:
                recs = list(self._pending.items())
            for msg_id, rec in recs:
                if rec.mode == "async":
                    if rec.deadline.expired():
                        with self._futures_lock:
                            self._pending.pop(msg_id, None)
                        retry_mod.note("async_dropped")
                        continue
                    if (now - rec.last_send >= attempt_iv
                            and self._resume_evt.is_set()):
                        try:
                            self.send(rec.frame)
                        except Exception:
                            continue
                        rec.attempts += 1
                        rec.last_send = _time.monotonic()
                        retry_mod.note("async_retries")
                if hang_s and not rec.dumped and now - rec.started > hang_s:
                    rec.dumped = True
                    retry_mod.dump_blocked_rpc(
                        rec, reason=f"in flight > {hang_s:.0f}s")

    # ---- failover ----
    def replace_conn(self, conn, hold_resend: bool = False):
        """Head failover: swap in a fresh control connection.  Unacked
        requests STAY registered — their idempotency keys make a resend
        exactly-once, so in-flight calls ride the new conn (resent by
        their blocked caller / the keeper) instead of erroring.  With
        ``hold_resend`` resends are gated until :meth:`release_resend`,
        so the re-registration handshake goes first on the new conn.
        Swap is atomic under both locks (request() never nests them)."""
        from ray_tpu._private.chaos import wrap_net_faults

        conn = wrap_net_faults(conn)
        with self._send_lock:
            with self._futures_lock:
                if hold_resend:
                    self._resume_evt.clear()
                old, self.conn = self.conn, conn
        try:
            old.close()
        except Exception:
            pass

    def release_resend(self):
        """Reconnect handshake done: resume (and immediately perform) the
        resend of every still-pending request on the new conn."""
        import time as _time

        self._resume_evt.set()
        with self._futures_lock:
            recs = list(self._pending.values())
        for rec in recs:
            try:
                self.send(rec.frame)
                rec.attempts += 1
                rec.last_send = _time.monotonic()
            except Exception:
                break

    def close(self):
        with self._futures_lock:
            self._closed = True
            pending, self._pending = dict(self._pending), {}
        try:
            self.conn.close()
        except Exception:
            pass
        err = exc.RayTpuError("connection closed")
        for rec in pending.values():
            if not rec.fut.done():
                rec.fut.set_exception(err)
        # Release any caller gated on a reconnect handshake so it can
        # observe _closed instead of sleeping out its deadline.
        self._resume_evt.set()


class _EnvOverlay:
    """Refcounted runtime-env env-var overlay for pooled workers.

    Concurrent execute_task threads (async/threaded actors) mutate the
    process-global os.environ; a naive per-task save/restore can permanently
    install another task's injected value.  Instead the *pristine* value of
    each key is recorded once (while any override is active) and restored
    when the last overriding task finishes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._orig: Dict[str, Optional[str]] = {}
        self._counts: Dict[str, int] = {}

    def apply(self, env_vars: Dict[str, Any]):
        import os

        with self._lock:
            for k, v in env_vars.items():
                k = str(k)
                if self._counts.get(k, 0) == 0:
                    self._orig[k] = os.environ.get(k)
                self._counts[k] = self._counts.get(k, 0) + 1
                os.environ[k] = str(v)

    def restore(self, env_vars: Dict[str, Any]):
        import os

        with self._lock:
            for k in env_vars:
                k = str(k)
                n = self._counts.get(k, 0)
                if n <= 1:
                    self._counts.pop(k, None)
                    old = self._orig.pop(k, None)
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                else:
                    self._counts[k] = n - 1

    def adopt(self, env_vars: Dict[str, Any]):
        """Make the current overrides permanent (actor-creation: the worker
        is dedicated to the actor from here on)."""
        with self._lock:
            for k in env_vars:
                k = str(k)
                self._counts.pop(k, None)
                self._orig.pop(k, None)


_env_overlay = _EnvOverlay()


class _WorkingDirOverlay:
    """runtime_env working_dir (reference: the working_dir plugin,
    python/ray/_private/runtime_env/working_dir.py — there the dir is
    uploaded to GCS and extracted per node; on this single-host plane the
    path is already local, so the overlay is chdir + sys.path).  Refcounted
    like _EnvOverlay: concurrent tasks with the same working_dir share one
    activation; mismatched concurrent dirs raise (one process, one cwd)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Optional[str] = None
        self._count = 0
        self._orig_cwd: Optional[str] = None

    def apply(self, working_dir: str):
        import os
        import sys

        with self._lock:
            path = os.path.abspath(working_dir)
            if not os.path.isdir(path):
                raise FileNotFoundError(
                    f"runtime_env working_dir {working_dir!r} does not "
                    "exist on this node")
            if self._count and self._active != path:
                raise RuntimeError(
                    "concurrent tasks with different working_dirs on one "
                    f"worker ({self._active} vs {path}); use separate "
                    "actors or max_concurrency=1")
            if self._count == 0:
                self._orig_cwd = os.getcwd()
                os.chdir(path)
                sys.path.insert(0, path)
                self._active = path
            self._count += 1

    def restore(self):
        import os
        import sys

        with self._lock:
            if self._count == 0:
                return
            self._count -= 1
            if self._count == 0:
                try:
                    sys.path.remove(self._active)
                except ValueError:
                    pass
                # Evict modules imported FROM the working_dir: a later task
                # (same pooled worker, different dir) must not hit a stale
                # sys.modules cache for a same-named module.
                prefix = self._active + os.sep
                for name, mod in list(sys.modules.items()):
                    mod_file = getattr(mod, "__file__", None) or ""
                    if mod_file.startswith(prefix):
                        sys.modules.pop(name, None)
                try:
                    os.chdir(self._orig_cwd)
                except OSError:
                    pass
                self._active = None

    def adopt(self):
        """Actor-creation: the working_dir stays for the actor's life —
        leave cwd/sys.path as applied, drop the refcount bookkeeping."""
        with self._lock:
            self._count = max(self._count - 1, 0)
            if self._count == 0:
                self._active = None
                self._orig_cwd = None


_workdir_overlay = _WorkingDirOverlay()

from ray_tpu._private.runtime_env_pkg import PyModulesOverlay  # noqa: E402

_pymods_overlay = PyModulesOverlay()


def _arena_lease_releaser(transport, oid_bin: bytes, holder_bin: bytes):
    """Standalone finalizer (must not capture the buffer owner) that returns
    this process's reader lease on an arena object to the head."""

    def release():
        try:
            transport.notify({"type": "arena_release", "oid": oid_bin,
                              "holder": holder_bin})
        except Exception:
            pass

    return release


# Sentinel: _put_object_deferred consumed the put AND its first local ref
# (owner-resident fast path) — no notify, no ObjectRef-side add_ref.
_OWNED_WITH_REF = {"type": "_owned_with_ref"}


# ---------------------------------------------------------------------------
# CoreWorker
# ---------------------------------------------------------------------------
class _DepsUnready(BaseException):
    """Raised during DIRECT-task arg resolution when a dependency is still
    pending at its owner: the worker bounces the task back to the submitter
    (who re-routes it through the head) rather than blocking the lease
    queue — the pending producer may be queued right behind this task.
    BaseException so user-level `except Exception` can't swallow it."""

    def __init__(self, oid):
        self.oid = oid


class TaskContext(threading.local):
    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.put_counter = 0
        self.task_name = ""
        self.direct_exec = False   # executing a direct-pushed task
        self.bounce_ok = False     # NORMAL direct task: may bounce deps
        self.arg_resolve = False   # inside execute_task arg resolution


class CoreWorker:
    def __init__(self, worker_id: WorkerID, node_id: NodeID, job_id: JobID,
                 transport, mode: str):
        self.worker_id = worker_id
        self.node_id = node_id
        self.job_id = job_id
        self.transport = transport
        self.mode = mode  # "driver" | "worker" | "local"
        try:
            _obs().set_identity(f"{mode}:{worker_id.hex()[:8]}",
                                node_id.hex())
        except Exception:
            pass
        # Ownership plane (reference: in-process memory store +
        # reference_count.h).  _owned always exists; the direct submitter +
        # server are attached by enable_direct() when the process supports
        # the direct transport (see _private/direct.py).
        from ray_tpu._private.direct import OwnedStore

        self._owned = OwnedStore()
        self._direct = None
        self._direct_server = None
        self.direct_addr: Optional[dict] = None
        self.host_key: str = ""
        self._borrowed: Dict[ObjectID, list] = {}  # oid -> [owner_addr, count]
        # Job-level defaults (reference: JobConfig — ray_namespace +
        # runtime_env applied to every task/actor the driver submits
        # unless per-call options override them).  Drivers get these set
        # at connect; pooled workers adopt them per executed task from
        # the task's job (see execute_task), cached per job id.
        self.namespace = "default"
        self.default_runtime_env: Optional[dict] = None
        self._job_config_cache: Dict[JobID, dict] = {}
        self.ctx = TaskContext()
        self.driver_task_id = TaskID.for_driver(job_id)
        # Out-of-task puts (driver threads): itertools.count.__next__ is
        # atomic at the C level, so no lock on the put hot path.
        import itertools

        self._put_counter = itertools.count(1)
        # Blocked-in-get depth (process-wide): while a worker blocks
        # waiting for an object it tells the head, which releases the
        # worker's cpu so dependency producers can schedule (reference:
        # NotifyDirectCallTaskBlocked, core_worker.cc).
        self._block_depth = 0
        self._block_lock = threading.Lock()
        self._local_refs: Dict[ObjectID, int] = {}
        self._refs_lock = threading.Lock()
        # In-process caches (memory store): resolved values + attached
        # segments.  Bounded LRU — long-lived pooled workers would otherwise
        # retain every object they ever resolved.
        from collections import OrderedDict

        self._value_cache: "OrderedDict[ObjectID, Any]" = OrderedDict()
        self._value_cache_cap = 256
        self._shm_registry: Dict[ObjectID, Any] = {}
        # Same-oid pull coalescing (thread level): oid -> (Event, leader
        # thread id).  Followers wait on the leader's seal instead of
        # racing the canonical segment create / duplicating wire bytes.
        self._pulls_inflight: Dict[ObjectID, tuple] = {}
        self._pulls_lock = threading.Lock()
        # Cooperative-broadcast peer server: serves ranges of objects
        # THIS process is still pulling (lazily started on first striped
        # pull with transfer_coop_broadcast on).
        self._peer_srv = None
        self._func_cache: Dict[bytes, Callable] = {}
        self._func_blobs: Dict[bytes, bytes] = {}
        self.actors: Dict[ActorID, Any] = {}
        self._closed = False
        # __del__ deferral: ObjectRef finalizers fire at arbitrary points —
        # notably inside transport.send's pickling while _send_lock is held
        # (a ref dropped by the pickler re-enters send → self-deadlock on
        # the non-reentrant lock) — so a dropped ref is queued here and a
        # drainer thread does the transport I/O.
        from collections import deque

        self._ref_gc_queue: "deque" = deque()
        self._ref_gc_wake = threading.Event()
        self._ref_gc_thread = threading.Thread(
            target=self._ref_gc_loop, name="rtpu-ref-gc", daemon=True)
        self._ref_gc_thread.start()

    # ---- reference counting ----
    def enable_direct(self, server, host_key: str):
        """Attach the direct transport: this process's listener (serving
        fetch/pin + optionally exec) and the caller-side submitter."""
        from ray_tpu._private.direct import DirectSubmitter

        self._direct_server = server
        self.direct_addr = server.address
        self.host_key = host_key
        self._direct = DirectSubmitter(self)

    def add_local_ref(self, oid: ObjectID, owner_addr: Optional[dict] = None):
        if self._closed:
            return
        # Owner path: this process holds the entry — count locally, never
        # talk to the head (EXTERN entries already mirror one holder there).
        if self._owned.add_ref(oid) is not None:
            return
        # Borrower path: register the borrow with the owner (reference:
        # borrow registration, reference_count.h:520) instead of the head.
        if owner_addr is not None and self._direct is not None:
            # rec = [owner_addr, count, pinned?]; the pin itself happens
            # OUTSIDE the refs lock (it can open a connection).  Ordering
            # (pin-before-unpin at the owner) comes from the handshake:
            # the unpin is deferred to whichever thread holds/reaches the
            # pinned state last (see remove_local_ref).
            with self._refs_lock:
                rec = self._borrowed.get(oid)
                if rec is None:
                    rec = self._borrowed[oid] = [owner_addr, 1, False]
                    register = True
                else:
                    rec[1] += 1
                    register = False
            if register:
                self._direct.pin_at_owner(
                    oid, owner_addr, b"bor:" + self.worker_id.binary())
                with self._refs_lock:
                    rec[2] = True
                    dead = rec[1] <= 0
                    if dead:
                        self._borrowed.pop(oid, None)
                if dead:  # every ref dropped while we were registering
                    self._direct.unpin_at_owner(
                        oid, owner_addr, b"bor:" + self.worker_id.binary())
            return
        with self._refs_lock:
            n = self._local_refs.get(oid, 0)
            self._local_refs[oid] = n + 1
            first = n == 0
        if first:
            # Fire-and-forget: the reply is a bare ack, and a blocking
            # round trip here can deadlock — refs are unpickled on
            # transport reader threads (conn.recv), which must never wait
            # on a reply only they can deliver.  Same-connection ordering
            # keeps add_ref ahead of any later remove_ref.
            try:
                self.transport.request_oneway(
                    "add_ref", {"oid": oid, "holder": self.worker_id.binary()})
            except Exception:
                pass

    def remove_local_ref_deferred(self, oid: ObjectID,
                                  owner_addr: Optional[dict] = None):
        """ObjectRef.__del__ entry point: no I/O on the caller's thread.

        Transition-based wakeup: the event is set only when the queue
        goes empty -> non-empty (one set per drain cycle, so the drainer
        can sleep long while idle) — a set per drop would hand the GIL
        to the drainer on every ObjectRef death (measured 4x slower
        small-put throughput)."""
        if self._closed:
            return
        q = self._ref_gc_queue
        q.append((oid, owner_addr))
        if len(q) == 1 or len(q) >= 4096:
            self._ref_gc_wake.set()

    def _drain_ref_gc_queue(self):
        # Head-side removals are coalesced: a burst of K dropped refs
        # costs one remove_ref_batch message instead of K remove_refs
        # (owner/borrow removals stay per-ref — they are local or ride
        # dedicated owner channels).
        batch: List[bytes] = []
        while self._ref_gc_queue:
            try:
                oid, owner_addr = self._ref_gc_queue.popleft()
            except IndexError:
                break
            try:
                self.remove_local_ref(oid, owner_addr, head_batch=batch)
            except Exception:
                pass
            if len(batch) >= 4096:
                self._send_remove_ref_batch(batch)
                batch = []
        if batch:
            self._send_remove_ref_batch(batch)

    def _send_remove_ref_batch(self, oids: List[bytes]):
        try:
            if len(oids) == 1:
                self.transport.request_oneway(
                    "remove_ref", {"oid": ObjectID(oids[0]),
                                   "holder": self.worker_id.binary()})
            else:
                self.transport.request_oneway(
                    "remove_ref_batch",
                    {"oids": oids, "holder": self.worker_id.binary()})
        except Exception:
            pass

    def _ref_gc_loop(self):
        while not self._closed:
            self._ref_gc_wake.wait(timeout=0.5)
            self._ref_gc_wake.clear()
            # Short settle: let a burst of drops batch before draining
            # (the wake fired on the FIRST drop of the batch).
            if self._ref_gc_queue:
                import time as _time

                _time.sleep(0.002)
            self._drain_ref_gc_queue()

    def remove_local_ref(self, oid: ObjectID, owner_addr: Optional[dict] = None,
                         head_batch: Optional[List[bytes]] = None):
        """Drop one local ref.  When ``head_batch`` is given, head-side
        removals are appended to it instead of sent (the ref-gc drainer
        flushes them as one remove_ref_batch)."""
        if self._closed:
            return

        def head_remove():
            if head_batch is not None:
                head_batch.append(oid.binary())
                return
            try:
                self.transport.request_oneway(
                    "remove_ref",
                    {"oid": oid, "holder": self.worker_id.binary()})
            except Exception:
                pass

        from ray_tpu._private.direct import EXTERN

        r = self._owned.remove_ref(oid)
        if r is not None:
            n, state = r
            if n <= 0:
                self._value_cache.pop(oid, None)
                self._drop_local_shm(oid)
                if state == EXTERN:
                    # Drop the mirrored holder in the head directory.
                    head_remove()
            return
        with self._refs_lock:
            rec = self._borrowed.get(oid)
            if rec is not None:
                rec[1] -= 1
                last_borrow = rec[1] <= 0 and rec[2]
                if last_borrow:
                    # Pin already registered: this thread sends the unpin.
                    # If the registering thread is still mid-pin (rec[2]
                    # False), IT will observe count<=0 and unpin.
                    self._borrowed.pop(oid, None)
            else:
                last_borrow = None
        if rec is not None:
            if last_borrow:
                self._value_cache.pop(oid, None)
                self._drop_local_shm(oid)
                if self._direct is not None:
                    self._direct.unpin_at_owner(
                        oid, rec[0], b"bor:" + self.worker_id.binary())
            return
        with self._refs_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n <= 0:
                self._local_refs.pop(oid, None)
            else:
                self._local_refs[oid] = n
            last = n <= 0
        if last:
            self._value_cache.pop(oid, None)
            self._drop_local_shm(oid)
            head_remove()

    # ---- put ----
    def current_task_id(self) -> TaskID:
        return self.ctx.task_id or self.driver_task_id

    def put(self, value: Any) -> ObjectRef:
        if _tracing().tracing_enabled():
            _obs().ensure_context()
        if self.ctx.task_id is None:
            # Outside task execution the put id hangs off the SHARED
            # driver task id, but put_counter is thread-local — two driver
            # threads would both count 1, 2, ... and silently alias each
            # other's objects (e.g. a StepPipeline submitting from a
            # worker thread).  Use the process-wide atomic counter.
            put_index = next(self._put_counter)
        else:
            self.ctx.put_counter += 1
            put_index = self.ctx.put_counter
        oid = ObjectID.for_put(self.current_task_id(), put_index)
        msg = self._put_object_deferred(oid, value, with_ref=True)
        if msg is _OWNED_WITH_REF:
            r = ObjectRef(oid, skip_adding_local_ref=True)
            r._owner_registered = True
            return r
        if msg is not None:
            self.transport.notify(msg)
        return ObjectRef(oid)

    def _next_put_id(self) -> ObjectID:
        if self.ctx.task_id is None:
            put_index = next(self._put_counter)
        else:
            self.ctx.put_counter += 1
            put_index = self.ctx.put_counter
        return ObjectID.for_put(self.current_task_id(), put_index)

    def put_many(self, values: Sequence[Any]) -> List[ObjectRef]:
        """Put a burst of K objects with O(1) control-plane messages.

        Bytes move exactly as in put() (owner store / arena / pooled shm
        segments), but the per-object ``seal``/``put_inline`` notifies are
        coalesced into one ``seal_batch``/``put_inline_batch`` message, and
        the head registers this process as holder of every store-resident
        object in the same message — so a K-put burst costs at most two
        head messages instead of up to 2K.  Item order inside each batch
        is submission order (the head applies them in order under one
        lock)."""
        plan: List[Tuple[ObjectID, str]] = []
        inline_items: List[dict] = []
        seal_items: List[dict] = []
        for value in values:
            oid = self._next_put_id()
            msg = self._put_object_deferred(oid, value, with_ref=True)
            if msg is _OWNED_WITH_REF:
                plan.append((oid, "seal"))  # ref pre-taken, like seal
                continue
            if msg is None:
                plan.append((oid, "owned"))
                continue
            t = msg.pop("type")
            if t == "put_inline":
                inline_items.append(msg)
                plan.append((oid, "inline"))
            elif t == "seal":
                # Holder rides the batch: pre-register the local ref and
                # let the head's batch handler record it, instead of one
                # add_ref message per object.
                seal_items.append(msg)
                with self._refs_lock:
                    self._local_refs[oid] = self._local_refs.get(oid, 0) + 1
                plan.append((oid, "seal"))
            else:  # arena_sealed — rare; keep its dedicated handler
                msg["type"] = t
                self.transport.notify(msg)
                plan.append((oid, "inline"))
        if inline_items:
            self.transport.notify({"type": "put_inline_batch",
                                   "items": inline_items})
        if seal_items:
            self.transport.notify({"type": "seal_batch",
                                   "items": seal_items,
                                   "holder": self.worker_id.binary()})
        refs: List[ObjectRef] = []
        for oid, kind in plan:
            if kind == "seal":
                r = ObjectRef(oid, skip_adding_local_ref=True)
                r._owner_registered = True
                refs.append(r)
            else:
                refs.append(ObjectRef(oid))
        return refs

    def put_object(self, oid: ObjectID, value: Any,
                   lineage_task: Optional[TaskID] = None):
        msg = self._put_object_deferred(oid, value, lineage_task)
        if msg is not None and msg is not _OWNED_WITH_REF:
            self.transport.notify(msg)

    def _put_object_deferred(self, oid: ObjectID, value: Any,
                             lineage_task: Optional[TaskID] = None,
                             with_ref: bool = False) -> Optional[dict]:
        """Write the object's bytes; return the control-plane notify (or
        None when no head message is needed) so callers batching a burst
        of puts (put_many) can coalesce K notifies into one.  With
        ``with_ref`` an owner-resident put also takes the first local ref
        inside the same store lock (returns _OWNED_WITH_REF)."""
        s = ser.serialize(value)
        size = ser.packed_size(s)
        # Refs nested in the put value must outlive this process's own
        # refs to them: the head pins them under the put's lifetime
        # (res:<oid> holders).  The notify rides this conn BEFORE any
        # later ref-gc drop, so the pin can never lose the race.
        contained = ([c.binary() for c in s.contained_refs]
                     if s.contained_refs else None)
        if size <= INLINE_OBJECT_THRESHOLD:
            meta, data = ser.pack(s)
            if self._direct is not None:
                # Owner-resident put: zero head traffic (reference: puts
                # land in the owner's in-process store, memory_store.h:43;
                # other processes fetch from the owner).
                if with_ref:
                    self._owned.put_with_ref(oid, meta, data)
                    self._cache_value(oid, value)
                    return _OWNED_WITH_REF
                self._owned.put(oid, meta, data)
                self._cache_value(oid, value)
                return None
            self._cache_value(oid, value)
            return {"type": "put_inline", "oid": oid.binary(),
                    "meta": meta, "data": data, "contained": contained,
                    "lineage_task": lineage_task}
        store = getattr(self.transport, "store_for",
                        lambda n: None)(self.node_id)
        if store is not None:
            view = store.arena_write(oid, size)
            if view is not None:
                try:
                    meta = ser.pack_into(s, view)
                finally:
                    view.release()
                store.arena_seal(oid, meta)
                self._cache_value(oid, value)
                return {"type": "arena_sealed", "oid": oid.binary(),
                        "node_id": self.node_id.binary(), "size": size,
                        "contained": contained,
                        "lineage_task": lineage_task}
            # In-process pooled path: allocate from the node store (a
            # recycled, already-faulted pool segment in steady state —
            # no shm_open, no kernel page-zeroing), pack straight in.
            buf = store.create(oid, size, overcommit=True)
            try:
                meta = ser.pack_into(s, buf)
                store.seal(oid, meta)
            except BaseException:
                store.delete(oid)
                raise
            self._cache_value(oid, value)
            return {"type": "seal", "oid": oid.binary(),
                    "node_id": self.node_id.binary(), "size": size,
                    "meta": meta, "segment": store.segment_of(oid),
                    "contained": contained,
                    "lineage_task": lineage_task}
        meta, segment = self._write_to_store(oid, s, size)
        self._cache_value(oid, value)
        return {"type": "seal", "oid": oid.binary(),
                "node_id": self.node_id.binary(),
                "size": size, "meta": meta, "segment": segment,
                "contained": contained,
                "lineage_task": lineage_task}

    def _write_to_store(self, oid: ObjectID, s: ser.SerializedObject,
                        size: int) -> Tuple[bytes, Optional[str]]:
        """Create the shared-memory segment directly (zero round trips) and
        hand ownership to the raylet via the seal notification.  Returns
        (meta, segment): segment is None for the canonical per-object
        name, or the unique fallback name used when the canonical one is
        taken on this machine — a retried/reconstructed task re-creating
        an output whose original segment still exists (dead virtual node
        mid-teardown, co-hosted agent) must not fail or unlink a segment
        another store may still serve."""
        import os as _os

        from multiprocessing import shared_memory

        segment = None
        try:
            shm = shared_memory.SharedMemory(
                name=store_mod._segment_name(oid), create=True,
                size=max(1, size))
        except FileExistsError:
            segment = (store_mod._segment_name(oid) + "_r"
                       + _os.urandom(4).hex())
            shm = shared_memory.SharedMemory(
                name=segment, create=True, size=max(1, size))
        store_mod.untrack(shm)
        store_mod.track_for_exit(shm)
        view = shm.buf[:size]
        try:
            meta = ser.pack_into(s, view)
        finally:
            view.release()
        shm.close()
        return meta, segment

    # ---- get ----
    def get(self, refs, timeout: Optional[float] = None):
        if _tracing().tracing_enabled():
            _obs().ensure_context()
        single = isinstance(refs, ObjectRef)
        if not single and not isinstance(refs, (list, tuple)):
            raise TypeError(
                f"get() expects an ObjectRef or a list of ObjectRefs, "
                f"got {type(refs).__name__}")
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        resolved: dict = {}
        if len(ref_list) > 1:
            # One round trip resolves everything already available; only
            # the stragglers take the blocking per-object path.
            # Dedup: a repeated ref must not be granted two arena leases
            # when only one materialize (and lease release) will happen.
            # Owner-resident (non-EXTERN) refs never go to the head.
            from ray_tpu._private.direct import EXTERN

            def _head_resident(oid: ObjectID) -> bool:
                e = self._owned.lookup(oid)
                return e is None or e.state == EXTERN

            missing = list(dict.fromkeys(
                r.id for r in ref_list if r.id not in self._value_cache
                and _head_resident(r.id)))
            if missing:
                batch = self.transport.request("resolve_batch",
                                               {"oids": missing})
                resolved = dict(batch or {})
        out = []
        value_cache = self._value_cache
        owned_lookup = self._owned.lookup
        from ray_tpu._private.direct import READY

        try:
            for r in ref_list:
                oid = r.id
                msg = resolved.pop(oid.binary(), None)
                if msg is not None and oid not in value_cache:
                    out.append(self._materialize(oid, msg))
                    continue
                if msg is not None and msg.get("kind") == "arena":
                    # Batch granted a lease but the cache won: give the
                    # lease back instead of dropping it on the floor.
                    self._release_arena_lease(oid)
                # Fast path: cached value or owner-resident READY bytes
                # (the common case for direct-task results).
                v = value_cache.get(oid, value_cache)
                if v is not value_cache:
                    out.append(v)
                    continue
                e = owned_lookup(oid)
                if e is not None and e.state == READY:
                    value, _ = ser.unpack(e.meta, memoryview(e.data))
                    self._cache_value(oid, value)
                    out.append(value)
                    continue
                out.append(self._get_one(oid, timeout,
                                         getattr(r, "owner_addr", None)))
        finally:
            # If an earlier ref's materialization raised, release the
            # leases of every unconsumed arena resolution — otherwise the
            # slots stay pinned until the driver disconnects.
            for oid_bin, msg in resolved.items():
                if msg.get("kind") == "arena":
                    try:
                        self._release_arena_lease(ObjectID(oid_bin))
                    except Exception:
                        pass
        return out[0] if single else out

    def get_many(self, refs: Sequence[ObjectRef],
                 timeout: Optional[float] = None) -> List[Any]:
        """Batch get: one resolve_batch round trip covers every object
        already available; stragglers fall back to the blocking path.
        Each wire pull picks its holder least-loaded-first (in-flight
        stream counts + observed per-peer bandwidth, see
        TransferClient.rank_sources) so a gather burst spreads across
        replicas instead of draining the first-listed holder.
        Semantically identical to get(list) — the name documents intent
        at call sites that gather bursts (SampleBatch gathers, dataset
        block fetches)."""
        return self.get(list(refs), timeout)

    def _prime_resolutions(self, oids: List[ObjectID]) -> None:
        """One resolve_batch request materializes every already-available
        head-resident object into the value cache, so a task with K ref
        args costs one head round trip instead of K (stragglers keep the
        per-object blocking path)."""
        from ray_tpu._private.direct import EXTERN

        def _head_resident(oid: ObjectID) -> bool:
            e = self._owned.lookup(oid)
            return e is None or e.state == EXTERN

        missing = list(dict.fromkeys(
            o for o in oids if o not in self._value_cache
            and _head_resident(o)))
        if len(missing) < 2:
            return
        try:
            batch = self.transport.request("resolve_batch",
                                           {"oids": missing})
        except Exception:
            return
        for oid_bin, msg in (batch or {}).items():
            oid = ObjectID(oid_bin)
            if oid in self._value_cache:
                if msg.get("kind") == "arena":
                    self._release_arena_lease(oid)
                continue
            try:
                self._materialize(oid, msg)
            except Exception:
                pass  # the per-arg path re-raises with proper context
                # (arena failure paths inside _materialize already
                # released their lease)

    def _cache_value(self, oid: ObjectID, value):
        self._value_cache[oid] = value
        self._value_cache.move_to_end(oid)
        while len(self._value_cache) > self._value_cache_cap:
            old, _ = self._value_cache.popitem(last=False)
            self._drop_local_shm(old)

    def _drop_local_shm(self, oid: ObjectID) -> None:
        """Deterministically free this process's mapping for ``oid``:
        drop any cooperative-transfer partial record still holding the
        buffer, then defuse the segment handle through the weak-registry
        path (object_store.defuse_shm) so a consumer-held numpy/arrow
        view never surfaces a BufferError from SharedMemory.__del__."""
        h = self._shm_registry.pop(oid, None)
        if self._peer_srv is not None:
            try:
                if self._peer_srv.drop_partial(oid):
                    # Retract the directory advertisement so pullers stop
                    # being pointed at a source that no longer serves.
                    self.transport.notify({
                        "type": "object_partial_drop",
                        "oid": oid.binary(),
                        "key": self.worker_id.binary()})
            except Exception:
                pass
        if h is None:
            return
        from multiprocessing import shared_memory

        if isinstance(h, shared_memory.SharedMemory):
            store_mod.defuse_shm(h)
        else:
            try:
                h.close()  # mmap over a spill file
            except (BufferError, ValueError, OSError):
                pass

    @contextlib.contextmanager
    def _blocked_in_get(self):
        """Tell the head this worker is blocked waiting for an object so
        its cpu can serve dependency producers meanwhile (reference:
        NotifyDirectCallTaskBlocked/Unblocked; raylet releases and later
        re-acquires the cpu, local_task_manager.cc).  No-op off-worker."""
        if self.mode != "worker":
            yield
            return
        with self._block_lock:
            self._block_depth += 1
            notify = self._block_depth == 1
        if notify:
            try:
                self.transport.notify({"type": "worker_blocked",
                                       "worker_id": self.worker_id.binary()})
            except Exception:
                pass
        try:
            yield
        finally:
            with self._block_lock:
                self._block_depth -= 1
                notify = self._block_depth == 0
            if notify:
                try:
                    self.transport.notify({
                        "type": "worker_unblocked",
                        "worker_id": self.worker_id.binary()})
                except Exception:
                    pass

    def _get_one(self, oid: ObjectID, timeout: Optional[float],
                 owner_addr: Optional[dict] = None):
        if oid in self._value_cache:
            self._value_cache.move_to_end(oid)
            return self._value_cache[oid]
        from ray_tpu._private.direct import ERROR, EXTERN, PENDING, READY

        entry = self._owned.lookup(oid)
        if entry is not None:
            if entry.state == PENDING:
                with self._blocked_in_get():
                    if not self._owned.wait_fulfilled(entry, timeout):
                        raise exc.GetTimeoutError(f"get({oid}) timed out")
            state, meta, data = entry.state, entry.meta, entry.data
            if state == READY:
                value, _ = ser.unpack(meta, memoryview(data))
                self._cache_value(oid, value)
                return value
            if state == ERROR:
                err, _ = ser.unpack(meta, memoryview(data))
                if isinstance(err, BaseException):
                    raise err
                raise exc.RayTpuError(str(err))
            # EXTERN: bytes live in the shared store / head — fall through.
        elif owner_addr is not None and self._direct is not None:
            nowait = self.ctx.bounce_ok and self.ctx.arg_resolve
            if nowait:
                msg = self._direct.fetch_from_owner(oid, owner_addr, timeout,
                                                    nowait=True)
            else:
                with self._blocked_in_get():
                    msg = self._direct.fetch_from_owner(oid, owner_addr,
                                                        timeout)
            if msg is not None:
                k = msg["k"]
                if k == "pending":
                    raise _DepsUnready(oid)
                if k == "bytes":
                    value, _ = ser.unpack(msg["m"], memoryview(msg["d"]))
                    self._cache_value(oid, value)
                    return value
                if k == "error":
                    err, _ = ser.unpack(msg["m"], memoryview(msg["d"]))
                    if isinstance(err, BaseException):
                        raise err
                    raise exc.RayTpuError(str(err))
                if k == "missing":
                    # The owner no longer holds it and never externalized
                    # it: unless the head knows the object, it is gone.
                    if not self.transport.request("object_info",
                                                  {"oid": oid}):
                        raise exc.ObjectLostError(
                            f"object {oid} was freed by its owner")
                # k == "extern" (or missing-but-head-knows): head path.
            else:
                # Owner unreachable (process died): the head may still hold
                # an externalized copy; otherwise the object died with its
                # owner (reference: owner failure => ObjectLostError).
                if not self.transport.request("object_info", {"oid": oid}):
                    raise exc.ObjectLostError(
                        f"object {oid} lost: its owner is gone")
        with self._blocked_in_get():
            msg = self.transport.request("get_locations",
                                         {"oid": oid, "timeout": timeout})
        return self._materialize(oid, msg)

    def _materialize(self, oid: ObjectID, msg: dict,
                     pull_failovers: int = 2):
        kind = msg["kind"]
        if kind == "inline":
            value, _ = ser.unpack(msg["meta"], memoryview(msg["data"]))
            self._cache_value(oid, value)
            return value
        if kind == "store":
            try:
                shm = store_mod.attach(oid, msg.get("segment"))
            except FileNotFoundError:
                raise exc.ObjectLostError(f"object {oid} vanished from the store")
            value, _ = ser.unpack(msg["meta"], shm.buf)
            self._cache_value(oid, value)
            self._shm_registry[oid] = shm  # keep mapping alive for zero-copy views
            return value
        if kind == "arena":
            import weakref

            import numpy as np

            from ray_tpu._native import ArenaReader

            # The head granted this process a reader lease on the arena slot
            # when it handed out this resolution; the slot will not be
            # recycled until we release it (plasma in-use-count semantics).
            try:
                view = ArenaReader.view(msg["store"], msg["offset"],
                                        msg["size"], msg["capacity"])
            except FileNotFoundError:
                self._release_arena_lease(oid)
                raise exc.ObjectLostError(f"arena object {oid} vanished")
            try:
                # Wrap the raw view in a weakref-able carrier: every
                # zero-copy array deserialized out of this object keeps a
                # buffer chain back to `owner`, so its finalizer fires
                # exactly when the last view is garbage-collected.
                owner = np.frombuffer(view, dtype=np.uint8)
                value, _ = ser.unpack(msg["meta"], memoryview(owner))
            except BaseException:
                self._release_arena_lease(oid)
                raise
            if ser.num_oob_buffers(msg["meta"]):
                weakref.finalize(
                    owner, _arena_lease_releaser(
                        self.transport, oid.binary(),
                        self.worker_id.binary()))
            else:
                # Nothing in `value` views the arena (in-band pickle only).
                self._release_arena_lease(oid)
            self._cache_value(oid, value)
            return value
        if kind == "spilled":
            # Same-host spill file: zero-copy mmap read (reference:
            # restore-on-get, spilled_object_reader.h).
            import mmap

            try:
                with open(msg["path"], "rb") as f:
                    if msg["size"] > 0:
                        buf = mmap.mmap(f.fileno(), 0,
                                        access=mmap.ACCESS_READ)
                    else:
                        buf = f.read()
            except (FileNotFoundError, ValueError):
                raise exc.ObjectLostError(
                    f"spilled object {oid} vanished from disk")
            value, _ = ser.unpack(msg["meta"], memoryview(buf))
            self._cache_value(oid, value)
            self._shm_registry[oid] = buf  # keep the mapping alive
            return value
        if kind == "pull":
            return self._pull_and_materialize(oid, msg,
                                              _failovers=pull_failovers)
        if kind == "error":
            err, _ = ser.unpack(msg["meta"], memoryview(msg["data"]))
            if isinstance(err, BaseException):
                raise err
            raise exc.RayTpuError(str(err))
        raise exc.RayTpuError(f"bad resolution kind {kind}")

    def _transfer_client(self):
        if getattr(self, "_xfer_client", None) is None:
            from ray_tpu._private.transfer import TransferClient

            self._xfer_client = TransferClient(self.transport.authkey)
        return self._xfer_client

    def _pull_and_materialize(self, oid: ObjectID, msg: dict,
                              _failovers: int = 2):
        """Cross-host read with location failover: try every holder the
        directory named; when ALL of them fail (the serving node died
        mid-pull), re-resolve through the head — which by then has run
        its node-death protocol and points at a replica, a spill restore,
        or a reconstruction — instead of erroring on the first sever.
        Reference: pull_manager.h:52 retrying against updated locations.

        Concurrent same-oid pulls in THIS process coalesce: one leader
        thread lands the bytes (one segment, one wire stream), followers
        wait on its seal and read the cached value."""
        if not msg.get("_rechecked"):
            # Prefetch race: the scheduler may have landed these bytes in
            # THIS host's store after the resolution was handed out — one
            # control round trip can turn a wire pull into a segment
            # attach (and refreshes stale holder addresses either way).
            try:
                fresh = self.transport.request(
                    "get_locations", {"oid": oid, "recheck": True})
            except Exception:
                fresh = None
            if fresh and fresh.get("kind") != "pull":
                return self._materialize(oid, fresh,
                                         pull_failovers=_failovers)
            if fresh:
                fresh["_rechecked"] = True
                msg = fresh
        from ray_tpu._private import transfer as transfer_mod

        cur = threading.get_ident()
        while True:
            with self._pulls_lock:
                rec = self._pulls_inflight.get(oid)
                if rec is None:
                    ev = threading.Event()
                    self._pulls_inflight[oid] = (ev, cur)
                    break
                if rec[1] == cur:
                    # The leader's own failover hop (re-resolve path
                    # recursing through _materialize): stay leader.
                    return self._pull_resolved(oid, msg, _failovers)
                ev = rec[0]
            transfer_mod._stat_add("coalesced_pulls")
            from ray_tpu._private.config import CONFIG

            ev.wait(float(CONFIG.transfer_timeout_s) + 30.0)
            if oid in self._value_cache:
                self._value_cache.move_to_end(oid)
                return self._value_cache[oid]
            # Leader failed (its caller got the error) or we timed out:
            # loop to take leadership and pull ourselves.
        try:
            return self._pull_resolved(oid, msg, _failovers)
        finally:
            with self._pulls_lock:
                self._pulls_inflight.pop(oid, None)
            ev.set()

    def _pull_resolved(self, oid: ObjectID, msg: dict, _failovers: int):
        ok, value = self._try_striped_pull(oid, msg)
        if ok:
            return value
        last_err: Optional[BaseException] = None
        addr_list = list(msg.get("addrs") or [msg["addr"]])
        if len(addr_list) > 1:
            # Least-loaded holder first (per-peer stream counts + EWMA
            # bandwidth): batched get_many gathers spread across
            # replicas instead of all draining the first-listed one.
            addr_list = self._transfer_client().rank_sources(addr_list)
        for addr in addr_list:
            try:
                return self._pull_once(oid, tuple(addr), msg["size"],
                                       local_partial=bool(
                                           msg.get("local_partial")))
            except (KeyError, EOFError, OSError, BrokenPipeError) as e:
                last_err = e  # dead/stale holder: try the next one
        if _failovers <= 0:
            raise exc.ObjectLostError(
                f"object {oid} could not be pulled from any holder: "
                f"{last_err}")
        # Every named holder failed.  Give the head a beat to notice the
        # death, then re-resolve (blocking like get): the reply is the
        # recovered resolution or the object's typed loss error.
        import time as _time

        _time.sleep(0.2)
        fresh = self.transport.request("get_locations", {"oid": oid})
        return self._materialize(oid, fresh, pull_failovers=_failovers - 1)

    def _peer_server(self):
        """This process's cooperative transfer server: store-less, serves
        only the ranges of objects we are mid-pull on (or just sealed)."""
        if self._peer_srv is None:
            from ray_tpu._private.transfer import ObjectTransferServer

            self._peer_srv = ObjectTransferServer(
                None, self.transport.authkey)
        return self._peer_srv

    def _try_striped_pull(self, oid: ObjectID, msg: dict):
        """Multi-source chunk-range pull into the canonical destination
        segment, re-serving landed ranges to concurrent pullers
        (cooperative broadcast).  Returns (True, value) when this path
        landed the object; (False, None) when it does not apply or
        failed — the caller's single-stream holder loop + head
        re-resolution remains the correctness path."""
        from ray_tpu._private import transfer as transfer_mod
        from ray_tpu._private.config import CONFIG

        size = int(msg.get("size") or 0)
        if size < int(CONFIG.transfer_stripe_min_bytes):
            return False, None
        coop = bool(CONFIG.transfer_coop_broadcast)
        addrs = [tuple(a) for a in (msg.get("addrs") or [msg["addr"]])]
        if not (coop or len(addrs) > 1 or msg.get("sources")):
            return False, None
        shm = membuf = None
        try:
            from multiprocessing import shared_memory

            shm = shared_memory.SharedMemory(
                name=store_mod._segment_name(oid), create=True, size=size)
            store_mod.untrack(shm)
            store_mod.track_for_exit(shm)
        except FileExistsError:
            if msg.get("local_partial") and coop:
                # A same-host striped pull owns the canonical segment:
                # wait for its seal instead of pulling the bytes twice
                # (_pull_once's local_partial path).
                return False, None
            # The name is taken by a puller we cannot wait on (another
            # host's worker on a shared-/dev/shm test box, or a stale
            # leak): stripe into an anonymous buffer — multi-source
            # scheduling and partial serving still apply, only the
            # zero-copy local seal is lost.
            membuf = bytearray(size)
        except Exception:
            return False, None  # shm unavailable: plain path falls back
        chunkb = int(msg.get("chunk") or CONFIG.transfer_chunk_bytes) \
            or transfer_mod.CHUNK
        nchunks = max(1, (size + chunkb - 1) // chunkb)
        src_list = [(tuple(a), set(c) if c is not None else None)
                    for a, c in (msg.get("sources") or [])] \
            or [(a, None) for a in addrs]
        peer = own_addr = None
        if coop:
            try:
                peer = self._peer_server()
                own_addr = tuple(peer.address)
                src_list = [s for s in src_list if s[0] != own_addr]
            except Exception:
                peer = None
        key = self.worker_id.binary()

        def progress(off, ln):
            # A landed range becomes servable + advertised: concurrent
            # pullers of this object stripe off us from here on.
            if peer is None:
                return
            fresh = peer.mark_range(oid, off, ln)
            if fresh:
                try:
                    self.transport.notify({
                        "type": "object_partial", "oid": oid.binary(),
                        "key": key, "addr": list(own_addr),
                        "chunk": chunkb, "total": nchunks,
                        "chunks": fresh, "size": size})
                except Exception:
                    pass

        def refresh():
            # Mid-pull source discovery: the directory may have gained
            # partial holders (other receivers of the same broadcast)
            # since our resolution was handed out.
            try:
                fresh = self.transport.request(
                    "get_locations", {"oid": oid, "recheck": True})
            except Exception:
                return None
            if not fresh or fresh.get("kind") != "pull":
                return None
            out = []
            for a, c in (fresh.get("sources")
                         or [[a, None] for a in (fresh.get("addrs")
                                                 or [])]):
                t = tuple(a)
                if own_addr is None or t != own_addr:
                    out.append((t, set(c) if c is not None else None))
            return out

        import time as _time

        tc = None
        try:
            from ray_tpu.util.tracing import tracing_enabled

            if tracing_enabled():
                tc = _obs().get_context()
        except Exception:
            pass
        if peer is not None:
            peer.register_partial(
                oid, shm.buf if shm is not None else membuf, size, chunkb)
        view = shm.buf[:size] if shm is not None else memoryview(membuf)
        t0 = _time.time()
        pulled = False
        try:
            meta, stats = transfer_mod.pull_striped(
                self._transfer_client(), oid, size, src_list, view,
                meta_hint=msg.get("meta"), chunk=chunkb, tc=tc,
                refresh=refresh if coop else None, progress=progress)
            if meta is None:
                raise OSError(f"striped pull of {oid}: no source knew "
                              "the serialization meta")
            pulled = True
            if peer is not None:
                # On success the partial advertisement stays: for a
                # sealed segment it is redundant with the full-holder
                # entry but keeps serving already-connected pullers; for
                # the anonymous-buffer mode it IS this process's serve
                # surface (dropped when the object is freed).
                peer.complete_partial(oid, meta)
            if shm is not None:
                self.transport.notify({
                    "type": "seal", "oid": oid.binary(),
                    "node_id": self.node_id.binary(), "size": size,
                    "meta": meta})
            if tc is not None:
                # Puller-side stripe span for the PR 19 timeline: how
                # many sources fed this pull and how many bytes striped.
                try:
                    _obs().record(
                        "transfer.pull", t0, _time.time(), ctx=tc,
                        oid=oid.hex(), striped_bytes=size,
                        sources=len(stats["bytes_from"]),
                        partial_ranges=stats["partial_ranges"])
                except Exception:
                    pass
            value, _ = ser.unpack(
                meta, shm.buf[:size] if shm is not None
                else memoryview(membuf))
            self._cache_value(oid, value)
            if shm is not None:
                self._shm_registry[oid] = shm
            return True, value
        except BaseException:  # noqa: BLE001 — clean up, then decide
            if peer is not None:
                peer.drop_partial(oid)
                try:
                    self.transport.notify({
                        "type": "object_partial_drop",
                        "oid": oid.binary(), "key": key})
                except Exception:
                    pass
            if shm is not None:
                try:
                    store_mod.retrack(shm)  # unlink() re-unregisters
                    shm.unlink()
                    shm.close()
                except Exception:
                    pass
            if pulled:
                raise  # bytes landed but seal/unpack failed: a real error
            return False, None  # wire failure: single-stream failover
        finally:
            try:
                view.release()
            except BufferError:
                pass  # a serve thread still drains a range slice

    def _pull_once(self, oid: ObjectID, addr: tuple, size: int,
                   local_partial: bool = False):
        """One pull attempt against one holder: stream the object into
        THIS node's store, seal the local replica (so the directory
        learns the new location and neighbors read locally), then
        materialize zero-copy from the local segment.  Reference:
        pull_manager.h:52 + chunked push push_manager.h:29."""
        client = self._transfer_client()
        shm = None
        try:
            from multiprocessing import shared_memory

            shm = shared_memory.SharedMemory(
                name=store_mod._segment_name(oid), create=True,
                size=max(1, size))
            store_mod.untrack(shm)
            store_mod.track_for_exit(shm)
        except FileExistsError:
            # Another local reader is already landing this object.  When
            # the directory said a SAME-HOST striped pull is in progress
            # ("local_partial"), briefly wait for its seal: attaching the
            # one canonical segment beats a redundant in-memory wire pull
            # of the same bytes.  Otherwise keep the old immediate
            # in-memory fallback (the creator may be another process we
            # know nothing about — or long dead, leaking the name).
            shm = None
            if local_partial:
                from ray_tpu._private.config import CONFIG

                if CONFIG.transfer_coop_broadcast:
                    got = self._await_local_seal(oid)
                    if got is not None:
                        return got
        except Exception:
            shm = None
        try:
            if shm is not None:
                view = shm.buf[:size]
                try:
                    meta, _ = client.pull(addr, oid, sink=view)
                finally:
                    view.release()
                self.transport.notify({
                    "type": "seal", "oid": oid.binary(),
                    "node_id": self.node_id.binary(), "size": size,
                    "meta": meta})
                value, _ = ser.unpack(meta, shm.buf[:size])
                self._cache_value(oid, value)
                self._shm_registry[oid] = shm
                return value
            meta, data = client.pull(addr, oid)
            value, _ = ser.unpack(meta, memoryview(data))
            self._cache_value(oid, value)
            return value
        except BaseException:
            # ANY failure before the seal (missing object, transport death
            # mid-stream, unpack error) must unlink the pre-created segment:
            # nothing owns it yet, and a leaked name permanently poisons the
            # zero-copy pull path for this object on this host.
            if shm is not None:
                try:
                    store_mod.retrack(shm)  # unlink() re-unregisters
                    shm.unlink()
                    shm.close()
                except Exception:
                    pass
            # KeyError ("not in this store") propagates as-is: the caller
            # fails over to the next holder / a fresh head resolution.
            raise

    def _await_local_seal(self, oid: ObjectID):
        """Bounded wait for a same-host in-progress pull to seal, then
        materialize from its resolution (usually a local segment attach).
        Returns None when the leader vanishes or the wait times out —
        the caller falls back to its own in-memory pull."""
        import time as _time

        from ray_tpu._private.config import CONFIG

        deadline = _time.time() + min(15.0, float(CONFIG.transfer_timeout_s))
        while _time.time() < deadline:
            _time.sleep(0.05)
            try:
                fresh = self.transport.request(
                    "get_locations", {"oid": oid, "recheck": True})
            except Exception:
                return None
            if not fresh:
                return None
            if fresh.get("kind") not in ("pull", None):
                return self._materialize(oid, fresh)
            if fresh.get("kind") == "pull" \
                    and not fresh.get("local_partial"):
                return None  # leader failed/vanished: pull it ourselves
        return None

    def _release_arena_lease(self, oid: ObjectID):
        try:
            self.transport.notify({"type": "arena_release",
                                   "oid": oid.binary(),
                                   "holder": self.worker_id.binary()})
        except Exception:
            pass

    def get_async(self, ref: ObjectRef) -> Future:
        fut: Future = Future()
        owner = getattr(ref, "owner_addr", None)

        def run():
            try:
                fut.set_result(self._get_one(ref.id, None, owner))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    # ---- wait ----
    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        from ray_tpu._private.direct import ERROR, EXTERN, READY

        def _is_owner_local(r) -> bool:
            e = self._owned.lookup(r.id)
            if e is not None and e.state != EXTERN:
                return True
            # Borrowed refs resolve at their owner, which the head never
            # hears about — they must poll the owner, not the head.
            return e is None and getattr(r, "owner_addr", None) is not None

        if any(_is_owner_local(r) for r in refs):
            # Mixed owner-resident + head refs: short-poll both planes
            # (owner-side readiness is a local check; the head side is one
            # immediate-reply request per poll).
            import time as _time

            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            # Poll interval backs off exponentially: a long wait on slow
            # tasks must not spin the head (one wait_ready RPC per round)
            # or the owner connections at ~300 rounds/s forever.
            interval = 0.002
            with self._blocked_in_get():
                while True:
                    ready_bin = set()
                    head_side = []
                    for r in refs:
                        e = self._owned.lookup(r.id)
                        owner = getattr(r, "owner_addr", None)
                        if e is not None and e.state in (READY, ERROR):
                            ready_bin.add(r.id.binary())
                        elif r.id in self._value_cache:
                            ready_bin.add(r.id.binary())
                        elif e is None and owner is not None \
                                and self._direct is not None:
                            got = self._direct.fetch_from_owner(
                                r.id, owner, None, nowait=True)
                            if got is not None and got["k"] == "bytes":
                                # Keep the fetched value: later poll
                                # rounds hit the cache, and the get() is
                                # free (no refetch of big payloads).
                                value, _ = ser.unpack(
                                    got["m"], memoryview(got["d"]))
                                self._cache_value(r.id, value)
                                ready_bin.add(r.id.binary())
                            elif got is None or got["k"] != "pending":
                                # error/extern/missing: get() will
                                # resolve (or raise) promptly => ready.
                                ready_bin.add(r.id.binary())
                        elif e is None or e.state == EXTERN:
                            head_side.append(r)
                    if head_side and len(ready_bin) < num_returns:
                        got = self.transport.request(
                            "wait_ready",
                            {"oids": [r.id for r in head_side],
                             "num_returns": len(head_side), "timeout": 0.0})
                        ready_bin.update(got)
                    if len(ready_bin) >= num_returns or (
                            deadline is not None
                            and _time.monotonic() >= deadline):
                        break
                    sleep_for = interval
                    if deadline is not None:
                        sleep_for = min(sleep_for,
                                        max(0.0, deadline - _time.monotonic()))
                    _time.sleep(sleep_for)
                    interval = min(interval * 1.5, 0.1)
            ready, not_ready = [], []
            for r in refs:
                (ready if r.id.binary() in ready_bin
                 and len(ready) < num_returns else not_ready).append(r)
            return ready, not_ready
        with self._blocked_in_get():
            ready_bins = self.transport.request(
                "wait_ready",
                {"oids": [r.id for r in refs], "num_returns": num_returns,
                 "timeout": timeout})
        ready_set = set(ready_bins)
        ready, not_ready = [], []
        for r in refs:
            (ready if r.id.binary() in ready_set and len(ready) < num_returns
             else not_ready).append(r)
        return ready, not_ready

    # ---- task submission ----
    def make_args(self, args: Sequence[Any], kwargs: Dict[str, Any],
                  holds: Optional[list] = None
                  ) -> Tuple[List[TaskArg], Dict[str, TaskArg]]:
        def conv(v) -> TaskArg:
            if isinstance(v, ObjectRef):
                return TaskArg(ArgKind.REF, ref=v.id,
                               owner=v._effective_owner())
            s = ser.serialize(v)
            if ser.packed_size(s) > INLINE_OBJECT_THRESHOLD:
                # Large literal arg: promote to a put object, pass by ref
                # (reference inlines <100KB, else plasma: dependency_resolver).
                # The ObjectRef MUST outlive submission (callers stash
                # `holds` on the result ref / actor handle): dropping it
                # here lets the ref-gc drainer free the object in the
                # window before the executing worker resolves it — the
                # drop and the submit ride different threads, so conn
                # ordering cannot save us.
                ref = self.put(v)
                if holds is not None:
                    holds.append(ref)
                return TaskArg(ArgKind.REF, ref=ref.id,
                               owner=ref._effective_owner())
            return TaskArg(ArgKind.VALUE, value=ser.pack(s),
                           contained=list(s.contained_refs),
                           contained_owners=(s.contained_owners or None))
        return [conv(a) for a in args], {k: conv(v) for k, v in kwargs.items()}

    def _promote_owned_args(self, spec: TaskSpec):
        """Classic-path submit referencing owner-resident objects: push the
        bytes to the head directory first (ordered ahead of the submit on
        the same transport) so the head's arg pinning and the executing
        worker's resolution see them.  PENDING entries promote when their
        bytes arrive (the head's get_locations defers until then)."""
        from ray_tpu._private.direct import ERROR, PENDING, READY

        for arg in list(spec.args) + list(spec.kwargs.values()):
            for oid in ([arg.ref] if arg.ref is not None else []) + arg.contained:
                entry = self._owned.lookup(oid)
                if entry is None:
                    continue
                if entry.state == PENDING:
                    self._owned.set_promote_on_fulfill(oid)
                elif entry.state in (READY, ERROR):
                    self.promote_owned_to_head(oid)

    def promote_owned_to_head(self, oid: ObjectID) -> None:
        """Move an owner-resident inline object into the head directory and
        flip the local entry EXTERN (with refcount mirroring)."""
        from ray_tpu._private.direct import ERROR, EXTERN, READY
        from ray_tpu._private.task_spec import ERROR_META

        entry = self._owned.lookup(oid)
        if entry is None or entry.state not in (READY, ERROR):
            return
        meta = entry.meta if entry.state == READY else ERROR_META + entry.meta
        try:
            self.transport.notify({"type": "put_inline", "oid": oid.binary(),
                                   "meta": meta, "data": entry.data})
        except Exception:
            return
        had, has_refs = self._owned.make_extern(oid)
        if had and has_refs:
            try:
                self.transport.request_oneway(
                    "add_ref",
                    {"oid": oid, "holder": self.worker_id.binary()})
            except Exception:
                pass

    def _adopt_return_refs(self, spec: TaskSpec) -> List[ObjectRef]:
        """ObjectRefs for a direct submission: each adopts the submission
        ref pre-held by the owned entry (see OwnedStore.create_pending)."""
        refs = []
        for oid in spec.return_ids():
            r = ObjectRef(oid, skip_adding_local_ref=True)
            r._owner_registered = True
            refs.append(r)
        return refs

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        spec.owner_worker_id = self.worker_id
        spec.parent_task_id = self.current_task_id()
        if _tracing().tracing_enabled():
            spec.trace_ctx = _obs().context_for_outbound()
        if self._direct is not None and self._direct.submit_task(spec):
            return self._adopt_return_refs(spec)
        self._promote_owned_args(spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        tr = _tracing()
        with (tr.span("task.submit", task_name=spec.name)
              if tr.tracing_enabled() else contextlib.nullcontext()):
            if tr.tracing_enabled():
                # Re-parent to the submit span (recorded, driver-side) so
                # the worker's execute spans anchor a cross-process edge.
                spec.trace_ctx = _obs().context_for_outbound()
            self.transport.request_oneway("submit", {"spec": spec})
        return refs

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        spec.owner_worker_id = self.worker_id
        spec.parent_task_id = self.current_task_id()
        if _tracing().tracing_enabled():
            spec.trace_ctx = _obs().context_for_outbound()
        if self._direct is not None and self._direct.submit_actor_task(spec):
            return self._adopt_return_refs(spec)
        self._promote_owned_args(spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        tr = _tracing()
        with (tr.span("actor_task.submit", task_name=spec.name)
              if tr.tracing_enabled() else contextlib.nullcontext()):
            if tr.tracing_enabled():
                # Re-parent to the submit span (recorded, driver-side) so
                # the actor's execute spans anchor a cross-process edge.
                spec.trace_ctx = _obs().context_for_outbound()
            self.transport.request_oneway("actor_call", {"spec": spec})
        return refs

    # ---- function resolution ----
    def register_func_blob(self, func_hash: bytes, blob: bytes) -> None:
        """Record a function blob at message-receive time so stripped
        re-sends (see DirectChannel.exec) can always resolve, even when
        concurrent actor threads execute out of order."""
        self._func_blobs.setdefault(func_hash, blob)

    def load_function(self, blob: Optional[bytes],
                      func_hash: Optional[bytes]) -> Callable:
        key = func_hash or hashlib.sha256(blob).digest()
        fn = self._func_cache.get(key)
        if fn is None:
            if blob is None:
                blob = self._func_blobs.get(key)
                if blob is None:
                    raise exc.RayTpuError(
                        "function blob missing for a stripped task spec")
            fn = cloudpickle.loads(blob)
            self._func_cache[key] = fn
        return fn

    # ---- task execution ----
    def _job_config(self, job_id: JobID) -> dict:
        """Fetch-and-cache the job's config so nested submissions and
        named-actor lookups inside workers see the job's namespace and
        runtime_env defaults (reference: JobConfig propagation)."""
        cfg = self._job_config_cache.get(job_id)
        if cfg is None:
            try:
                cfg = self.transport.request(
                    "job_config", {"job_id": job_id.binary()}) or {}
            except Exception:
                # Transient head trouble: fall back for THIS task but do
                # not cache — caching {} would silently strip the job's
                # namespace/runtime_env for the rest of the worker's life.
                return {}
            self._job_config_cache[job_id] = cfg
        return cfg

    def execute_task(self, spec: TaskSpec) -> dict:
        """Run a task and build the task_done message (does not send it)."""
        import time as _time

        self.ctx.task_id = spec.task_id
        self.ctx.task_name = spec.name
        self.ctx.put_counter = 0
        saved_trace_ctx = None
        tracing_on = _tracing().tracing_enabled()
        if tracing_on:
            obs = _obs()
            # Execute inside the submitter's trace, and flush a begin
            # marker BEFORE running: if this process is SIGKILLed
            # mid-task, the head already holds evidence of what died.
            saved_trace_ctx = obs.adopt_spec_context(spec)
            obs.record_instant("task.begin", task_name=spec.name,
                              task_id=spec.task_id.hex())
            if self.mode == "worker":
                obs.flush(self.transport)
        # Adopt the submitting job's defaults for the task's duration
        # (pooled workers serve many jobs; restored in the finally).
        saved_job_defaults = (self.namespace, self.default_runtime_env)
        job_cfg = self._job_config(spec.job_id) if self.mode == "worker" \
            else {}
        if job_cfg:
            if job_cfg.get("namespace"):
                self.namespace = job_cfg["namespace"]
            if job_cfg.get("runtime_env"):
                self.default_runtime_env = job_cfg["runtime_env"]
        start_ts = _time.time()
        error = None
        error_str = None
        results: List[TaskResult] = []
        env_vars: Dict[str, Any] = {}
        workdir_applied = False
        pymods_applied = False
        renv = spec.runtime_env
        try:
            if renv:
                # Runtime env (lite): per-task/actor env vars (reference:
                # python/ray/_private/runtime_env/ plugin architecture).
                # Pooled workers execute many tasks: overlay the keys and
                # restore the pristine values afterwards so one task's env
                # does not leak into the next (the reference instead
                # dedicates workers to a runtime env).
                env_vars = renv.get("env_vars") or {}
                if env_vars:
                    _env_overlay.apply(env_vars)
                working_dir = renv.get("working_dir")
                if working_dir:
                    _workdir_overlay.apply(working_dir)
                    workdir_applied = True
                py_modules = renv.get("py_modules")
                if py_modules:
                    from ray_tpu._private.runtime_env_pkg import ensure_local

                    roots = [ensure_local(u, self.transport)
                             for u in py_modules]
                    _pymods_overlay.apply(roots)
                    pymods_applied = True
                unsupported = set(renv) - {"env_vars", "working_dir",
                                           "py_modules"}
                if unsupported:
                    raise exc.RayTpuError(
                        f"runtime_env fields {sorted(unsupported)} are not "
                        "supported (pip/conda need package egress; this "
                        "environment has none)")
            if spec.args or spec.kwargs:
                self.ctx.arg_resolve = True
                try:
                    ref_oids = [a.ref for a in
                                list(spec.args) + list(spec.kwargs.values())
                                if a.kind == ArgKind.REF]
                    if len(ref_oids) > 1:
                        # Coalesced resolution: one head round trip for
                        # every already-available ref arg instead of one
                        # get_locations per arg.
                        self._prime_resolutions(ref_oids)
                    args = [self._resolve_arg(a) for a in spec.args]
                    kwargs = {k: self._resolve_arg(a)
                              for k, a in spec.kwargs.items()}
                finally:
                    self.ctx.arg_resolve = False
            else:
                args, kwargs = [], {}
            tr = _tracing()
            span = (tr.span("task.execute", task_name=spec.name,
                            task_type=spec.task_type.name,
                            task_id=spec.task_id.hex())
                    if tr.tracing_enabled() else None)
            try:
                if span is not None:
                    span.__enter__()
                if spec.task_type == TaskType.ACTOR_TASK:
                    instance = self.actors.get(spec.actor_id)
                    if instance is None:
                        raise exc.ActorDiedError(
                            "actor instance not found on worker")
                    method = getattr(instance, spec.method_name)
                    out = method(*args, **kwargs)
                    if _is_coroutine(out):
                        out = _run_coroutine(out)
                elif spec.task_type == TaskType.NORMAL:
                    fn = self.load_function(spec.func_blob, spec.func_hash)
                    out = fn(*args, **kwargs)
                elif spec.task_type == TaskType.ACTOR_CREATION:
                    cls = self.load_function(spec.func_blob, spec.func_hash)
                    self.actors[spec.actor_id] = cls(*args, **kwargs)
                    out = None
                else:
                    raise exc.RayTpuError(f"bad task type {spec.task_type}")
            finally:
                if span is not None:
                    span.__exit__(None, None, None)
            results = self._store_returns(spec, out)
        except _DepsUnready:
            raise  # bounced to the submitter by the worker loop
        except BaseException as e:  # noqa: BLE001 — errors are task results
            error_str = traceback.format_exc()
            terr = exc.TaskError(type(e).__name__, None, error_str, spec.name)
            s = ser.serialize(terr)
            error = ser.pack(s)
        finally:
            # Actor-creation env vars stay: the worker is dedicated to the
            # actor from here on (matching the reference's dedicated-worker
            # runtime-env model).
            if env_vars:
                if spec.task_type == TaskType.ACTOR_CREATION:
                    _env_overlay.adopt(env_vars)
                else:
                    _env_overlay.restore(env_vars)
            if workdir_applied:
                # Only rebalance if apply() actually incremented the
                # count — a failed apply must not decrement a concurrent
                # holder's activation.
                if spec.task_type == TaskType.ACTOR_CREATION:
                    _workdir_overlay.adopt()
                else:
                    _workdir_overlay.restore()
            if pymods_applied:
                if spec.task_type == TaskType.ACTOR_CREATION:
                    _pymods_overlay.adopt()
                else:
                    _pymods_overlay.restore()
            # Actor creation keeps the adopted defaults: the worker is
            # dedicated to this actor's job from here on.
            if spec.task_type != TaskType.ACTOR_CREATION:
                self.namespace, self.default_runtime_env = saved_job_defaults
            self.ctx.task_id = None
            if tracing_on:
                obs = _obs()
                if self.mode == "worker":
                    obs.flush(self.transport)
                obs.set_context(saved_trace_ctx)
        return {
            "type": "task_done",
            "task_id": spec.task_id.binary(),
            "worker_id": self.worker_id.binary(),
            "spec": spec,
            "results": results,
            "error": error,
            "error_str": error_str,
            "crashed": False,
            "start": start_ts,
            "end": _time.time(),
        }

    def _resolve_arg(self, arg: TaskArg):
        if arg.kind == ArgKind.REF:
            return self._get_one(arg.ref, None, getattr(arg, "owner", None))
        meta, data = arg.value
        value, _ = ser.unpack(meta, memoryview(data))
        return value

    def _store_returns(self, spec: TaskSpec, out) -> List[TaskResult]:
        if spec.num_returns == 0:
            return []
        values = [out] if spec.num_returns == 1 else list(out)
        if len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(values)} values")
        results = []
        for i, value in enumerate(values):
            oid = ObjectID.for_task_return(spec.task_id, i)
            s = ser.serialize(value)
            size = ser.packed_size(s)
            if size <= INLINE_OBJECT_THRESHOLD:
                contained = None
                if s.contained_refs and self.ctx.direct_exec:
                    # Contained-ref handover (reference_count.h:543): for
                    # SELF-owned refs, hold a `ret:` pin locally until the
                    # caller registers its `res:` pin (_on_done) — the pin
                    # is set before the done ships, so it cannot race.
                    # Refs this worker merely BORROWS are listed without a
                    # pre-pin: a remote `ret:` pin rides a different
                    # channel than the done and could arrive after the
                    # caller's unpin (leaking), so the caller just
                    # registers its `res:` pin promptly and the borrow
                    # chain's own pins cover the (small) window.
                    token = b"ret:" + spec.task_id.binary()
                    contained = []
                    for coid in s.contained_refs:
                        if self._owned.contains(coid):
                            self._owned.pin(coid, token)
                            contained.append((coid.binary(),
                                              self.direct_addr, True))
                        else:
                            owner = s.contained_owners.get(coid.binary())
                            if owner is not None and self._direct is not None:
                                contained.append((coid.binary(), owner,
                                                  False))
                            else:
                                # Head-counted nested ref (e.g. a shm-
                                # sealed put): hold a head-side ret: ref,
                                # ordered on this conn BEFORE our own
                                # ref-gc drop can arrive; the caller
                                # swaps it for a res: ref tied to the
                                # result entry (_take_contained_pins).
                                self.transport.request_oneway(
                                    "add_ref", {"oid": coid,
                                                "holder": token})
                                contained.append((coid.binary(), None,
                                                  False))
                elif s.contained_refs:
                    # Classic-path result: nested owner-resident refs must
                    # outlive this worker's local refs — promote them into
                    # the head directory, then let the head pin every
                    # nested ref under the result entry's lifetime
                    # (res:<result oid> holders, added when it records
                    # this result — ordered before our ref-gc drop).
                    contained = []
                    for coid in s.contained_refs:
                        if self._owned.contains(coid):
                            self.promote_owned_to_head(coid)
                        contained.append((coid.binary(), None, False))
                results.append(TaskResult(oid, inline=ser.pack(s),
                                          contained=contained))
            else:
                meta, segment = self._write_to_store(oid, s, size)
                self.transport.notify({
                    "type": "seal", "oid": oid.binary(),
                    "node_id": self.node_id.binary(), "size": size,
                    "meta": meta, "segment": segment,
                    "lineage_task": spec.task_id,
                    "contained": ([c.binary() for c in s.contained_refs]
                                  if s.contained_refs else None)})
                results.append(TaskResult(oid, in_store=True, size=size, meta=meta))
        return results

    def cancel_task(self, task_id: TaskID):
        """ray.cancel: direct in-flight tasks are cancelled by their owner
        (this process); everything else goes through the head."""
        if self._direct is not None and self._direct.cancel(task_id):
            return
        self.transport.request("cancel", {"task_id": task_id})

    def shutdown(self):
        # Drain deferred ref drops BEFORE closing: a ref dropped just
        # before shutdown must still send its remove_ref/unpin (the
        # synchronous __del__ path used to guarantee this).
        self._drain_ref_gc_queue()
        self._closed = True
        if self._direct is not None:
            try:
                self._direct.shutdown()
            except Exception:
                pass
        if self._direct_server is not None:
            try:
                self._direct_server.shutdown()
            except Exception:
                pass
        self.transport.close()


def _is_coroutine(obj) -> bool:
    import inspect

    return inspect.iscoroutine(obj)


def _run_coroutine(coro):
    import asyncio

    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# Global worker plumbing
# ---------------------------------------------------------------------------
global_worker: Optional[CoreWorker] = None


def set_global_worker(w: Optional[CoreWorker]):
    global global_worker
    global_worker = w


object_ref_mod._get_global_worker = lambda: global_worker
