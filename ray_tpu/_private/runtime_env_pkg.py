"""py_modules runtime env: content-hash packaging + a worker-side URI cache.

Reference: python/ray/_private/runtime_env/packaging.py (local dirs are
zipped, content-hashed into ``gcs://_ray_pkg_<hash>.zip`` URIs and pushed
to the GCS KV) and uri_cache.py (workers download/unpack once per URI).
TPU-native redesign: the zip bytes ride the head's existing KV plane
(namespace ``_pkgs``) over the control connection — no side channel, and
a restarted head repopulates from its snapshot like any other KV state.

Driver side: ``normalize_py_modules`` rewrites local paths / imported
modules in ``runtime_env["py_modules"]`` to ``pkg://<sha256>`` URIs,
uploading each package at most once per content hash.  Worker side:
``ensure_local`` materializes a URI into a per-node cache directory
(atomic rename, shared by all workers on the node) and
``_PyModulesOverlay`` prepends the cached roots to sys.path for the
task's duration — refcounted like the working_dir overlay, adopted for
the worker's lifetime on actor creation.
"""
from __future__ import annotations

import hashlib
import io
import os
import threading
import zipfile
from typing import List, Optional, Tuple

PKG_SCHEME = "pkg://"
KV_NAMESPACE = "_pkgs"
# Mirrors the reference's GCS_STORAGE_MAX_SIZE warning threshold
# (packaging.py): bigger uploads work but stall the control plane.
WARN_SIZE = 100 * 1024 * 1024

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _iter_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _EXCLUDE_DIRS)
        for f in sorted(filenames):
            if f.endswith((".pyc", ".pyo")):
                continue
            yield os.path.join(dirpath, f)


def package_path(path: str) -> Tuple[str, bytes]:
    """Zip a local directory (as a top-level package dir) or a single
    module file; returns (pkg://<hash>, zip_bytes).  The hash covers
    relative paths + file contents, so identical sources dedupe and any
    edit produces a fresh URI (reference: packaging.py hash semantics)."""
    path = os.path.abspath(path)
    h = hashlib.sha256()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isdir(path):
            base = os.path.basename(path.rstrip(os.sep))
            for fp in _iter_files(path):
                rel = os.path.join(base, os.path.relpath(fp, path))
                h.update(rel.encode())
                with open(fp, "rb") as fh:
                    data = fh.read()
                h.update(data)
                zf.writestr(rel, data)
        elif os.path.isfile(path):
            rel = os.path.basename(path)
            h.update(rel.encode())
            with open(path, "rb") as fh:
                data = fh.read()
            h.update(data)
            zf.writestr(rel, data)
        else:
            raise FileNotFoundError(f"py_modules entry {path!r} not found")
    return PKG_SCHEME + h.hexdigest(), buf.getvalue()


def _module_root(mod) -> str:
    """An imported module/package object → its source path (reference:
    py_modules accepts module objects, runtime_env/py_modules.py)."""
    f = getattr(mod, "__file__", None)
    if f is None:
        raise ValueError(f"module {mod!r} has no __file__ — only source "
                         "modules/packages can ship as py_modules")
    if os.path.basename(f).startswith("__init__."):
        return os.path.dirname(f)
    return f


# Driver-side upload memo: abspath -> (stat signature, uri).  The stat
# signature (file count + latest mtime + total size) cheaply invalidates
# when sources change; the content hash remains the authority.  A memo
# hit skips the zip+hash only — presence in THIS cluster's KV is still
# verified per call (a fresh init() or an unpersisted head restart wipes
# the KV while the process-global memo survives).
_upload_memo = {}
_memo_lock = threading.Lock()


def _kv_has(transport, uri: str) -> bool:
    try:
        keys = transport.request("kv", {"verb": "keys",
                                        "prefix": uri.encode(),
                                        "namespace": KV_NAMESPACE})
    except Exception:
        return False
    return bool(keys)


def _stat_sig(path: str):
    if os.path.isfile(path):
        st = os.stat(path)
        return (1, st.st_mtime_ns, st.st_size)
    n, mt, sz = 0, 0, 0
    for fp in _iter_files(path):
        try:
            st = os.stat(fp)
        except OSError:
            continue
        n += 1
        mt = max(mt, st.st_mtime_ns)
        sz += st.st_size
    return (n, mt, sz)


def normalize_py_modules(renv: Optional[dict], transport) -> Optional[dict]:
    """Rewrite local py_modules entries to pkg:// URIs, uploading to the
    head KV when the content hash is not already stored.  Entries that
    are already URIs pass through.  Returns a new runtime_env dict (the
    input is never mutated) or the input unchanged when there is nothing
    to do."""
    if not renv or not renv.get("py_modules"):
        return renv
    out: List[str] = []
    changed = False
    for entry in renv["py_modules"]:
        if isinstance(entry, str) and entry.startswith(PKG_SCHEME):
            out.append(entry)
            continue
        if not isinstance(entry, str):
            entry = _module_root(entry)
        path = os.path.abspath(entry)
        sig = _stat_sig(path)
        with _memo_lock:
            memo = _upload_memo.get(path)
        if memo is not None and memo[0] == sig \
                and _kv_has(transport, memo[1]):
            out.append(memo[1])
            changed = True
            continue
        uri, blob = package_path(path)
        if len(blob) > WARN_SIZE:
            import logging

            logging.getLogger(__name__).warning(
                "py_modules package %s is %dMB — large packages stall the "
                "control plane; ship data via the object store instead",
                path, len(blob) // (1024 * 1024))
        key = uri.encode()
        # overwrite=False: content-addressed, so a concurrent/previous
        # upload of the same hash is byte-identical.
        transport.request("kv", {"verb": "put", "key": key, "value": blob,
                                 "namespace": KV_NAMESPACE,
                                 "overwrite": False})
        with _memo_lock:
            _upload_memo[path] = (sig, uri)
        out.append(uri)
        changed = True
    if not changed:
        return renv
    new_env = dict(renv)
    new_env["py_modules"] = out
    return new_env


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------
def _cache_root() -> str:
    return os.environ.get("RTPU_PKG_CACHE",
                          os.path.join("/tmp", "rtpu_pkg_cache"))


def ensure_local(uri: str, transport) -> str:
    """Materialize a pkg:// URI into the node-local cache; returns the
    directory to put on sys.path.  Extract-to-temp + atomic rename makes
    concurrent workers on one node safe (uri_cache.py's one-download-per-
    URI property, without its bookkeeping process)."""
    if not uri.startswith(PKG_SCHEME):
        # Local-path entry (same-host convenience / tests): use in place.
        path = os.path.abspath(uri)
        if not os.path.exists(path):
            raise FileNotFoundError(f"py_modules entry {path!r} does not "
                                    "exist on this node")
        return os.path.dirname(path) if os.path.isfile(path) else \
            os.path.dirname(path.rstrip(os.sep))
    digest = uri[len(PKG_SCHEME):]
    target = os.path.join(_cache_root(), digest)
    if os.path.isdir(target):
        return target
    blob = transport.request("kv", {"verb": "get", "key": uri.encode(),
                                    "namespace": KV_NAMESPACE})
    if blob is None:
        raise FileNotFoundError(
            f"py_modules package {uri} not found in the cluster KV (was "
            "the uploading driver's head wiped without persistence?)")
    # Per-call scratch dir: two threads of one worker share a pid, so a
    # pid-suffixed path could be extracted into by one thread while the
    # other renames (or rmtree's) it — mkdtemp gives each materialization
    # its own publish candidate, and the atomic rename stays the only
    # cross-writer coordination point.
    import tempfile

    os.makedirs(_cache_root(), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=_cache_root(), prefix=digest + ".tmp.")
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # Lost the race to another worker: theirs is byte-identical.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


class PyModulesOverlay:
    """Refcounted sys.path prepend of package roots (the py_modules
    analogue of the working_dir overlay): concurrent tasks may share one
    active set; a different set while active is refused; restore evicts
    modules imported from the roots so pooled workers don't leak code
    between jobs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Optional[tuple] = None
        self._count = 0

    def apply(self, roots: List[str]):
        import sys

        key = tuple(roots)
        with self._lock:
            if self._count and self._active != key:
                raise RuntimeError(
                    "concurrent tasks with different py_modules on one "
                    f"worker ({self._active} vs {key}); use separate "
                    "actors or max_concurrency=1")
            if self._count == 0:
                for r in reversed(roots):
                    sys.path.insert(0, r)
                self._active = key
            self._count += 1

    def restore(self):
        import sys

        with self._lock:
            if self._count == 0:
                return
            self._count -= 1
            if self._count == 0:
                for r in self._active:
                    try:
                        sys.path.remove(r)
                    except ValueError:
                        pass
                    prefix = r + os.sep
                    for name, mod in list(sys.modules.items()):
                        mod_file = getattr(mod, "__file__", None) or ""
                        if mod_file.startswith(prefix):
                            sys.modules.pop(name, None)
                self._active = None

    def adopt(self):
        with self._lock:
            self._count = max(self._count - 1, 0)
            if self._count == 0:
                self._active = None
