"""Remote driver runtime: ``ray_tpu.init(address="host:port")``.

The reference's equivalent is a driver connecting to an existing cluster
(ray.init(address=...), python/ray/_private/worker.py:1043): the driver
process talks to the remote GCS/raylet over the network.  Here the driver

- opens one TCP control connection to the head (requests + notifications),
- embeds a small SharedMemoryStore + ObjectTransferServer so its own puts
  stay host-local yet remain pullable by the cluster, and
- registers as an unschedulable pseudo-node (head.add_remote_driver).
"""
from __future__ import annotations

import os
import threading
from multiprocessing.connection import Client
from typing import Optional

from ray_tpu._private.ids import JobID, NodeID, ObjectID, WorkerID
from ray_tpu.exceptions import HeadConnectionError
from ray_tpu._private.object_store import SharedMemoryStore
from ray_tpu._private.transfer import (
    ObjectTransferServer,
    wire_store_reporting,
)
from ray_tpu._private.worker import ConnTransport


class RemoteDriverRuntime:
    def __init__(self, address: str, authkey: bytes,
                 store_capacity: int = 512 * 1024**2,
                 job_config: Optional[dict] = None,
                 timeout: float = 30.0):
        import time as _time

        host, port = address.rsplit(":", 1)
        self._head_host, self._head_port = host, int(port)
        self._address = address
        self._job_config = job_config
        self.authkey = authkey
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self.host_key = os.urandom(8).hex()
        import tempfile

        self._spill_dir = tempfile.mkdtemp(prefix="rtpu_spill_")
        self.store = SharedMemoryStore(store_capacity,
                                       spill_dir=self._spill_dir)
        wire_store_reporting(self.store, lambda m: self.transport.send(m))
        self.conn = None
        start = _time.monotonic()
        try:
            self.xfer = ObjectTransferServer(self.store, authkey)
            # A head that just forked may have written its authkey file
            # before its listener accepts — retry refused connects within
            # the caller's timeout instead of failing on the first RST.
            while True:
                try:
                    self.conn = Client((host, int(port)), family="AF_INET",
                                       authkey=authkey)
                    break
                except (OSError, EOFError) as e:
                    refused = isinstance(e, ConnectionRefusedError)
                    if refused and _time.monotonic() - start < timeout:
                        _time.sleep(0.1)
                        continue
                    raise HeadConnectionError(
                        address, elapsed=_time.monotonic() - start,
                        socket_connected=False, detail=str(e)) from e
            self.transport = ConnTransport(self.conn, authkey)
            self.node_id: Optional[NodeID] = None
            self._registered = threading.Event()
            self._closing = False
            self._reader = threading.Thread(
                target=self._read_loop, name="rtpu-driver-reader",
                daemon=True)
            self._reader.start()
            # Package local py_modules BEFORE registration so the head's
            # job record (which pooled workers adopt for nested submits)
            # carries pkg:// URIs, never driver-local paths.
            if self._job_config and self._job_config.get("runtime_env"):
                from ray_tpu._private.runtime_env_pkg import \
                    normalize_py_modules

                self._job_config = dict(self._job_config)
                self._job_config["runtime_env"] = normalize_py_modules(
                    self._job_config["runtime_env"], self.transport)
            self._send_register()
            if not self._registered.wait(timeout):
                # Typed: the socket DID connect (Client succeeded) — the
                # head accepted us but never completed registration.
                raise HeadConnectionError(
                    address, elapsed=_time.monotonic() - start,
                    socket_connected=True,
                    detail="no driver_registered reply")
        except BaseException:
            self.shutdown()
            raise

    def _send_register(self):
        self.transport.send({
            "type": "register_driver",
            "worker_id": self.worker_id.binary(),
            "job_id": self.job_id,
            "job_config": self._job_config or {},
            "host_key": self.host_key,
            "transfer_addr": list(self.xfer.address),
            "pid": os.getpid(),
        })

    def _reconnect(self) -> bool:
        """Head restarted: retry within the reconnect window and
        re-register this driver (same identity/store) — reference: the
        GCS client reconnect window, ray_config_def.h:58-62."""
        import time

        from ray_tpu._private.config import CONFIG

        deadline = time.monotonic() + CONFIG.reconnect_window_s
        while time.monotonic() < deadline:
            time.sleep(1.0)
            try:
                conn = Client((self._head_host, self._head_port),
                              family="AF_INET", authkey=self.authkey)
            except Exception:
                continue
            self.conn = conn
            # Hold resends until re-registration lands on the new conn,
            # then resend unacked in-flight requests (idempotency-keyed,
            # so the head applies each at most once).
            self.transport.replace_conn(conn, hold_resend=True)
            try:
                self._send_register()
            except Exception:
                continue  # head died again mid-handshake: keep retrying
            self.transport.release_resend()
            return True
        return False

    def _read_loop(self):
        while True:
            try:
                # Read through the transport's conn (the fault-injection
                # wrapper when a net schedule is active).
                msg = self.transport.conn.recv()
            except (EOFError, OSError, BrokenPipeError):
                if self._closing or not self._reconnect():
                    self.transport.close()
                    return
                continue
            t = msg.get("type")
            if t == "reply":
                self.transport.on_reply(msg)
            elif t == "driver_registered":
                self.node_id = NodeID(msg["node_id"])
                self._registered.set()
            elif t == "store_adopt":
                self.store.adopt(ObjectID(msg["oid"]), msg["size"],
                                 msg["meta"], segment=msg.get("segment"))
            elif t == "store_delete":
                self.store.delete(ObjectID(msg["oid"]))
            elif t == "shutdown":
                self.transport.close()
                return

    def shutdown(self):
        self._closing = True
        try:
            if self.conn is not None:
                self.conn.close()
        except Exception:
            pass
        if getattr(self, "xfer", None) is not None:
            self.xfer.shutdown()
        self.store.shutdown()
        import shutil

        shutil.rmtree(self._spill_dir, ignore_errors=True)
