"""Remote-node agent: the per-host raylet process for multi-host clusters.

The reference runs one raylet binary per node (src/ray/raylet/main.cc) that
owns the node's plasma store, spawns workers, and serves object transfer.
This agent is that process for ray_tpu: it

- connects to the head over TCP (same authkey-HMAC framing as workers),
- registers the node (resources, host key, transfer address),
- owns the host's SharedMemoryStore + an ObjectTransferServer for pulls,
- spawns/kills worker subprocesses on command from the head's RemoteRaylet
  proxy (workers connect *directly* to the head over TCP for control; only
  store ownership and object bytes stay host-local),
- reports child exits so the head's health monitor sees remote deaths.

Start programmatically (cluster_utils.Cluster.add_remote_node) or:
    python -m ray_tpu._private.node_agent --address HOST:PORT \
        --authkey HEX --num-cpus 8 [--num-tpus 4] [--store-capacity BYTES]
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
import traceback
from multiprocessing.connection import Client
from typing import Dict

from ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ray_tpu._private.object_store import SharedMemoryStore
from ray_tpu._private.transfer import (
    ObjectTransferServer,
    wire_store_reporting,
)


class NodeAgent:
    def __init__(self, head_addr, authkey: bytes, resources: Dict[str, float],
                 store_capacity: int = 2 * 1024**3, max_workers: int = 64,
                 labels=None):
        self.head_addr = head_addr
        self.authkey = authkey
        self.resources = resources
        self.labels = labels or {}
        self.max_workers = max_workers
        self.host_key = os.urandom(8).hex()
        import tempfile

        self._spill_dir = tempfile.mkdtemp(prefix="rtpu_spill_")
        self.store = SharedMemoryStore(store_capacity,
                                       spill_dir=self._spill_dir)
        # should_spill stays None: without refcount visibility, spilling
        # everything evicted is the safe default.
        wire_store_reporting(self.store, self.send)
        self.xfer = ObjectTransferServer(self.store, authkey)
        from ray_tpu._private.chaos import wrap_net_faults

        # Fault-injection wrapper (identity no-op without a net schedule):
        # agent notifies label as notify:<type>, head pushes as
        # push:<type> (spawn_worker, store_adopt, ...).
        self.conn = wrap_net_faults(Client(tuple(head_addr), family="AF_INET",
                                           authkey=authkey))
        self._send_lock = threading.Lock()
        self._children: Dict[bytes, subprocess.Popen] = {}
        self._children_lock = threading.Lock()
        self._shutdown = threading.Event()
        self.node_id = None  # assigned by head in register reply
        self._stats_period = None  # head-resolved, set in register reply
        self._xfer_client = None  # lazy: durability replica pulls

    def send(self, msg: dict):
        with self._send_lock:
            self.conn.send(msg)

    def _register_msg(self) -> dict:
        msg = {
            "type": "register_node",
            "resources": self.resources,
            "labels": self.labels,
            "host_key": self.host_key,
            "transfer_addr": list(self.xfer.address),
            "store_capacity": self.store.capacity,
            "max_workers": self.max_workers,
            "pid": os.getpid(),
        }
        if self.node_id is not None:
            # Re-registration after a head restart: keep our identity and
            # hand over the surviving worker processes for adoption.
            msg["node_id"] = self.node_id.binary()
            with self._children_lock:
                msg["workers"] = [
                    {"worker_id": wid,
                     "tpu_chips": getattr(p, "_rtpu_chips", [])}
                    for wid, p in self._children.items()]
        return msg

    def _reconnect(self) -> bool:
        """Head connection died: retry within the reconnect window (the
        head may be restarting from its snapshot — reference: the GCS
        reconnect window, ray_config_def.h:58-62)."""
        from ray_tpu._private.config import CONFIG

        deadline = time.monotonic() + CONFIG.reconnect_window_s
        while not self._shutdown.is_set() and time.monotonic() < deadline:
            time.sleep(1.0)
            try:
                from ray_tpu._private.chaos import wrap_net_faults

                conn = wrap_net_faults(
                    Client(tuple(self.head_addr), family="AF_INET",
                           authkey=self.authkey))
            except Exception:
                continue
            with self._send_lock:
                try:
                    conn_old, self.conn = self.conn, conn
                except Exception:
                    continue
            try:
                conn_old.close()
            except Exception:
                pass
            try:
                self.send(self._register_msg())
            except Exception:
                continue  # head died again mid-handshake: keep retrying
            return True
        return False

    def run(self):
        self.send(self._register_msg())
        threading.Thread(target=self._reap_loop, name="rtpu-agent-reap",
                         daemon=True).start()
        threading.Thread(target=self._memory_loop, name="rtpu-agent-mem",
                         daemon=True).start()
        threading.Thread(target=self._stats_loop, name="rtpu-agent-stats",
                         daemon=True).start()
        threading.Thread(target=self._heartbeat_loop, name="rtpu-agent-hb",
                         daemon=True).start()
        try:
            while not self._shutdown.is_set():
                try:
                    msg = self.conn.recv()
                except (EOFError, OSError):
                    if self._shutdown.is_set() or not self._reconnect():
                        break
                    continue
                self._handle(msg)
        finally:
            self.shutdown()

    def _chaos_site(self, op: str):
        """Node-level kill site: a schedule match SIGKILLs the agent AND
        every worker child — whole-node loss, no cleanup, exactly what a
        preempted/OOM-killed host looks like to the head."""
        from ray_tpu._private.chaos import check_die

        if not check_die(op):
            return
        import signal

        with self._children_lock:
            procs = list(self._children.values())
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)

    def _handle(self, msg: dict):
        t = msg.get("type")
        self._chaos_site("node_agent_msg")
        try:
            if t == "node_registered":
                self.node_id = NodeID(msg["node_id"])
                if "node_stats_period_s" in msg:
                    self._stats_period = float(msg["node_stats_period_s"])
                try:
                    from ray_tpu import observability as obs

                    obs.set_identity(
                        f"agent:{self.node_id.hex()[:8]}",
                        self.node_id.hex())
                except Exception:
                    pass
            elif t == "spawn_worker":
                self._chaos_site("node_agent_spawn")
                self._spawn_worker(msg)
            elif t == "kill_worker":
                self._kill_worker(msg["worker_id"])
            elif t == "store_adopt":
                self.store.adopt(ObjectID(msg["oid"]), msg["size"],
                                 msg["meta"], segment=msg.get("segment"))
            elif t == "store_delete":
                self.store.delete(ObjectID(msg["oid"]))
            elif t == "store_pull":
                # Durability replica: pull the object from the named
                # holder into OUR store (off the reader thread — a pull
                # can move gigabytes) and ack with the replica's segment.
                threading.Thread(target=self._store_pull, args=(msg,),
                                 name="rtpu-agent-pull",
                                 daemon=True).start()
            elif t == "store_backup":
                oid = ObjectID(msg["oid"])
                self.store.backup(oid)  # spill_callback reports the record
            elif t == "shutdown":
                self._shutdown.set()
        except Exception:
            traceback.print_exc()

    def _store_pull(self, msg: dict):
        """Pull with holder failover and a short retry ladder: the named
        source may not serve the object YET (its seal raced the async
        store_adopt on that host) or may have died — try every holder
        the head named, backing off between rounds.  Used by both the
        durability plane and the scheduler's arg prefetch; a permanent
        failure is silent (the reader's demand pull is the correctness
        path)."""
        oid = ObjectID(msg["oid"])
        addrs = [tuple(a) for a in (msg.get("addrs") or [msg["addr"]])]
        try:
            if self._xfer_client is None:
                from ray_tpu._private.transfer import TransferClient

                self._xfer_client = TransferClient(self.authkey)
            meta = data = None
            striped = self._store_pull_striped(oid, msg)
            if striped is not None:
                meta, data = striped
            if data is None:
                for attempt in range(5):
                    for addr in addrs:
                        try:
                            meta, data = self._xfer_client.pull(addr, oid)
                            break
                        except Exception:
                            meta = data = None
                    if data is not None or self._shutdown.is_set():
                        break
                    time.sleep(0.05 * (2 ** attempt))
            if data is None:
                return
            seg = self.store.put_replica(oid, meta, data)
            self.send({"type": "object_replicated", "oid": oid.binary(),
                       "size": len(data), "meta": meta, "segment": seg})
        except Exception:
            traceback.print_exc()

    def _store_pull_striped(self, oid: ObjectID, msg: dict):
        """Multi-source leg of the replica/prefetch pull: stripe chunk
        ranges across every holder the head named (full holders + any
        cooperative partial holders in ``sources``), advertising our own
        landed ranges so concurrent pullers of the same object feed off
        this agent instead of the origin.  Returns (meta, bytes) or None
        (any failure falls back to the single-stream retry ladder)."""
        from ray_tpu._private.config import CONFIG

        size = int(msg.get("size") or 0)
        if size < int(CONFIG.transfer_stripe_min_bytes):
            return None
        coop = bool(CONFIG.transfer_coop_broadcast)
        addrs = [tuple(a) for a in (msg.get("addrs") or [msg["addr"]])]
        if not (coop or len(addrs) > 1 or msg.get("sources")):
            return None
        from ray_tpu._private import transfer as transfer_mod

        chunkb = int(msg.get("chunk") or CONFIG.transfer_chunk_bytes) \
            or transfer_mod.CHUNK
        nchunks = max(1, (size + chunkb - 1) // chunkb)
        own_addr = tuple(self.xfer.address)
        src_list = [(tuple(a), set(c) if c is not None else None)
                    for a, c in (msg.get("sources") or [])] \
            or [(a, None) for a in addrs]
        src_list = [s for s in src_list if s[0] != own_addr]
        if not src_list:
            return None
        buf = bytearray(size)
        key = None
        if coop and self.node_id is not None:
            key = b"na:" + self.node_id.binary()
            self.xfer.register_partial(oid, buf, size, chunkb)

        def progress(off, ln):
            if key is None:
                return
            fresh = self.xfer.mark_range(oid, off, ln)
            if fresh:
                try:
                    self.send({"type": "object_partial",
                               "oid": oid.binary(), "key": key,
                               "addr": list(own_addr), "chunk": chunkb,
                               "total": nchunks, "chunks": fresh,
                               "size": size})
                except Exception:
                    pass

        try:
            meta, _stats = transfer_mod.pull_striped(
                self._xfer_client, oid, size, src_list,
                memoryview(buf), meta_hint=msg.get("meta"),
                chunk=chunkb, progress=progress)
            if meta is None:
                return None
            if key is not None:
                self.xfer.complete_partial(oid, meta)
            return meta, buf  # bytes-like: put_replica copies it once
        except Exception:
            return None
        finally:
            if key is not None:
                # put_replica lands the bytes in OUR store, which the
                # object_replicated ack registers as a full holder — the
                # in-progress partial advertisement is obsolete either way.
                self.xfer.drop_partial(oid)
                try:
                    self.send({"type": "object_partial_drop",
                               "oid": oid.binary(), "key": key})
                except Exception:
                    pass

    def _heartbeat_loop(self):
        """Liveness lease renewal: the head declares this node dead when
        heartbeats go silent past node_lease_timeout_s (any other agent
        message also renews — this just bounds the idle silence)."""
        from ray_tpu._private.config import CONFIG

        period = max(0.1, CONFIG.node_heartbeat_period_s)
        while not self._shutdown.is_set():
            time.sleep(period)
            self._chaos_site("node_agent_tick")
            try:
                self.send({"type": "heartbeat"})
            except Exception:
                pass  # head restarting: reconnect loop handles it

    def _spawn_worker(self, msg: dict):
        env = dict(os.environ)
        env.update(msg.get("env") or {})
        if env.get("JAX_PLATFORMS") == "cpu":
            # CPU-only worker: skip the site hook's eager accelerator
            # registration + jax import (see raylet.spawn_worker).
            env.pop("PALLAS_AXON_POOL_IPS", None)
        from ray_tpu._private import inject_pkg_pythonpath

        inject_pkg_pythonpath(env)
        env["RAY_TPU_HEAD_ADDR"] = f"{self.head_addr[0]}:{self.head_addr[1]}"
        env.pop("RAY_TPU_HEAD_SOCKET", None)
        env["RAY_TPU_AUTHKEY"] = self.authkey.hex()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.default_worker"],
            env=env)
        proc._rtpu_spawned = time.monotonic()
        chips = (msg.get("env") or {}).get("TPU_VISIBLE_CHIPS")
        proc._rtpu_chips = ([int(c) for c in chips.split(",")]
                            if chips else [])
        with self._children_lock:
            self._children[msg["worker_id"]] = proc

    def _kill_worker(self, worker_id: bytes):
        with self._children_lock:
            proc = self._children.pop(worker_id, None)
        if proc is not None:
            try:
                proc.kill()
            except Exception:
                pass

    def _reap_loop(self):
        """Report child exits so the head can run its death handling even
        when the worker died before opening its control connection."""
        while not self._shutdown.is_set():
            time.sleep(0.5)
            with self._children_lock:
                items = list(self._children.items())
            for wid, proc in items:
                code = proc.poll()
                if code is not None:
                    with self._children_lock:
                        self._children.pop(wid, None)
                    try:
                        self.send({"type": "worker_exit", "worker_id": wid,
                                   "code": code})
                    except Exception:
                        pass  # head restarting: reconnect loop handles it

    def _memory_loop(self):
        """Host memory-pressure relief for THIS node (the head's monitor
        only reads the head host's memory; remote workers would otherwise
        be at the mercy of the kernel OOM-killer, which can take the
        agent/store down with them).  Kills the newest child under
        pressure — one per period, like the head-side pacing; the head's
        death handling retries/fails the victim's work.  Policy-blind by
        design: the agent has no task/actor visibility (that state lives
        in the head), so it cannot apply the ranked head-side policies —
        newest-child is the LIFO approximation."""
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.memory_monitor import host_memory_usage_fraction

        period = CONFIG.memory_monitor_refresh_ms / 1000.0
        threshold = CONFIG.memory_usage_threshold
        test_file = CONFIG.memory_monitor_test_file
        if period <= 0:
            return
        while not self._shutdown.is_set():
            time.sleep(period)
            usage = 0.0
            if test_file:
                try:
                    with open(test_file) as f:
                        usage = float(f.read().strip() or 0.0)
                except (OSError, ValueError):
                    usage = 0.0
            else:
                usage = host_memory_usage_fraction()
            if usage < threshold:
                continue
            with self._children_lock:
                items = list(self._children.items())
            now = time.monotonic()
            victim = None
            for wid, proc in items:
                # Spawn grace: a worker needs ~2s to boot; killing it
                # before it can run anything just spawn-loops the retry.
                if proc.poll() is None and \
                        now - getattr(proc, "_rtpu_spawned", 0.0) > 3.0:
                    victim = (wid, proc)  # dict order: newest spawn last
            if victim is None:
                continue
            try:
                # Mark BEFORE the kill on the same ordered conn the exit
                # report rides, so the head types the death as an OOM
                # (OutOfMemoryError w/ usage, retryable) instead of a
                # generic worker crash.
                self.send({"type": "worker_oom",
                           "worker_id": victim[0], "usage": usage})
            except Exception:
                pass
            try:
                victim[1].kill()
            except Exception:
                pass

    def _stats_loop(self):
        """Per-node usage snapshots → head (reference: the dashboard
        reporter agent per node).  The period is re-read each tick: the
        head ships its resolved value in the registration reply (the
        agent's own CONFIG never sees head-side _system_config
        overrides), which may land after this thread starts."""
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.node_stats import collect_node_stats

        while not self._shutdown.is_set():
            period = (self._stats_period if self._stats_period is not None
                      else CONFIG.node_stats_period_s)
            if period <= 0:
                time.sleep(1.0)  # disabled (possibly until the handshake)
                continue
            time.sleep(period)
            with self._children_lock:
                n_workers = len(self._children)
            try:
                frame = {"type": "node_stats",
                         "stats": collect_node_stats(
                             store=self.store, num_workers=n_workers)}
                try:
                    from ray_tpu import observability as obs
                    from ray_tpu.util.tracing import tracing_enabled

                    if tracing_enabled():
                        # Agent-side spans (transfer serving, pulls) ride
                        # the stats cadence instead of their own frames.
                        spans = obs.drain_spans()
                        if spans:
                            frame["spans"] = spans
                except Exception:
                    pass
                self.send(frame)
            except Exception:
                pass  # head restarting: reconnect loop handles it

    def shutdown(self):
        self._shutdown.set()
        with self._children_lock:
            procs = list(self._children.values())
            self._children.clear()
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        self.xfer.shutdown()
        self.store.shutdown()
        import shutil

        shutil.rmtree(self._spill_dir, ignore_errors=True)
        try:
            self.conn.close()
        except Exception:
            pass


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="head HOST:PORT")
    p.add_argument("--authkey", default=None,
                   help="hex authkey (default: RAY_TPU_AUTHKEY env)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=0.0)
    p.add_argument("--resources", default=None,
                   help='extra resources as JSON, e.g. \'{"nodeA": 1}\'')
    p.add_argument("--store-capacity", type=int, default=2 * 1024**3)
    p.add_argument("--max-workers", type=int, default=64)
    args = p.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    authkey = bytes.fromhex(args.authkey or os.environ["RAY_TPU_AUTHKEY"])
    ncpu = args.num_cpus if args.num_cpus is not None else os.cpu_count() or 1
    resources = {"CPU": float(ncpu)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    if args.resources:
        import json

        resources.update(json.loads(args.resources))
    agent = NodeAgent((host, int(port)), authkey, resources,
                      store_capacity=args.store_capacity,
                      max_workers=args.max_workers)
    agent.run()


if __name__ == "__main__":
    main()
