"""Chrome-trace timeline export (reference: ray.timeline() →
chrome_tracing_dump, python/ray/_private/profiling.py:43 over core-worker
profile events, src/ray/core_worker/profile_event.h) plus an in-process
span recorder for driver-side hot-path instrumentation (pipeline dispatch
and drain spans from ray_tpu.parallel.mesh_group.StepPipeline, device
prefetch spans from ray_tpu.data.prefetch).

The recorder is deliberately dumb and allocation-cheap: a bounded deque of
dicts behind one lock, no I/O, no KV round trips — it must be safe to call
once per training step without perturbing the thing it measures.  Readers
(tools/perf_smoke.py, tests) pull spans with ``recorded_spans``; the chrome
trace export merges them as one extra lane so overlap is visible in
chrome://tracing next to the task timeline.

When the tracing plane is on (ray_tpu.observability), every recorded
span is ALSO stamped with the active (or explicitly passed) trace
context and mirrored into the cluster span ring, so the
``mpmd_stage_*`` / ``rollout_*`` / ``flow_*`` / ``replay_*`` families
assemble into cross-process traces instead of staying anonymous.
perf_counter timestamps are rebased to wall clock at record time so
they merge with task events from other processes.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import List, Optional

# Bounded: a forgotten long-running pipeline must not grow driver memory.
_MAX_RECORDED_SPANS = 8192
_recorded: "deque" = deque(maxlen=_MAX_RECORDED_SPANS)
_recorded_lock = threading.Lock()


def record_span(name: str, start: float, end: float,
                _trace_ctx=None, _root=False, **args) -> None:
    """Record one completed span (timestamps from time.perf_counter()).

    Used by the step pipeline ("pipeline_dispatch"/"pipeline_drain", with
    step=<idx>) and the device prefetcher ("prefetch_h2d").  Thread-safe;
    never raises.  ``_trace_ctx`` pins the span to an explicit
    (trace_id, parent_span_id) pair for emitters that run off the
    submitting thread (flow stage workers); otherwise the thread's
    active context is stamped.  ``_root=True`` records the span AS the
    context's root (span_id = ctx[1]) — the mint point uses it once per
    trace so children parented to the root id resolve to a real span
    and cross-process flow arrows have an anchor."""
    try:
        with _recorded_lock:
            _recorded.append({"name": name, "start": float(start),
                              "end": float(end), "args": dict(args)})
        from ray_tpu.util.tracing import tracing_enabled

        if tracing_enabled():
            from ray_tpu import observability as obs

            # perf_counter → wall clock, rebased at record time.
            offset = time.time() - time.perf_counter()
            kw = {}
            if _root and _trace_ctx is not None:
                kw = {"span_id": _trace_ctx[1], "parent_id": None}
            obs.record(name, float(start) + offset, float(end) + offset,
                       ctx=_trace_ctx, **kw, **args)
    except Exception:
        pass


def recorded_spans(name: Optional[str] = None,
                   clear: bool = False) -> List[dict]:
    """Snapshot recorded spans (optionally filtered by name), oldest first."""
    with _recorded_lock:
        spans = list(_recorded)
        if clear:
            _recorded.clear()
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    return spans


def clear_recorded_spans() -> None:
    with _recorded_lock:
        _recorded.clear()


def chrome_tracing_dump(task_events: List[dict],
                        filename: Optional[str] = None,
                        include_recorded: bool = False,
                        spans: Optional[List[dict]] = None) -> List[dict]:
    """Convert the state API's task list into chrome://tracing events.

    ``spans`` (TraceStore records) merge in with per-node pid lanes,
    per-process tid lanes, and cross-process flow arrows — see
    ray_tpu.observability.timeline.  ``include_recorded=True`` appends
    the in-process span recorder's entries as a separate lane so
    pipeline dispatch/drain overlap shows up against the task timeline."""
    from ray_tpu.observability.timeline import build_chrome_trace

    extra = None
    if include_recorded:
        extra = [{
            "name": s["name"],
            "cat": "SPAN",
            "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": (s["end"] - s["start"]) * 1e6,
            "pid": "ray_tpu",
            "tid": "spans",
            "args": s["args"],
        } for s in recorded_spans()]
    events = build_chrome_trace(task_events, spans or [],
                                extra_events=extra)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
