"""Chrome-trace timeline export (reference: ray.timeline() →
chrome_tracing_dump, python/ray/_private/profiling.py:43 over core-worker
profile events, src/ray/core_worker/profile_event.h)."""
from __future__ import annotations

import json
from typing import List, Optional


def chrome_tracing_dump(task_events: List[dict],
                        filename: Optional[str] = None) -> List[dict]:
    """Convert the state API's task list into chrome://tracing events."""
    events = []
    for t in task_events:
        if t.get("start") is None or t.get("end") is None:
            continue
        events.append({
            "name": t["name"],
            "cat": t.get("type", "TASK"),
            "ph": "X",  # complete event
            "ts": t["start"] * 1e6,
            "dur": (t["end"] - t["start"]) * 1e6,
            "pid": "ray_tpu",
            "tid": (t.get("worker_id") or "driver")[:12],
            "args": {"task_id": t["task_id"], "attempt": t.get("attempt", 0),
                     "status": t.get("status")},
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
