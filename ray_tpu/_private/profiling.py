"""Chrome-trace timeline export (reference: ray.timeline() →
chrome_tracing_dump, python/ray/_private/profiling.py:43 over core-worker
profile events, src/ray/core_worker/profile_event.h) plus an in-process
span recorder for driver-side hot-path instrumentation (pipeline dispatch
and drain spans from ray_tpu.parallel.mesh_group.StepPipeline, device
prefetch spans from ray_tpu.data.prefetch).

The recorder is deliberately dumb and allocation-cheap: a bounded deque of
dicts behind one lock, no I/O, no KV round trips — it must be safe to call
once per training step without perturbing the thing it measures.  Readers
(tools/perf_smoke.py, tests) pull spans with ``recorded_spans``; the chrome
trace export merges them as one extra lane so overlap is visible in
chrome://tracing next to the task timeline.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from typing import List, Optional

# Bounded: a forgotten long-running pipeline must not grow driver memory.
_MAX_RECORDED_SPANS = 8192
_recorded: "deque" = deque(maxlen=_MAX_RECORDED_SPANS)
_recorded_lock = threading.Lock()


def record_span(name: str, start: float, end: float, **args) -> None:
    """Record one completed span (timestamps from time.perf_counter()).

    Used by the step pipeline ("pipeline_dispatch"/"pipeline_drain", with
    step=<idx>) and the device prefetcher ("prefetch_h2d").  Thread-safe;
    never raises."""
    try:
        with _recorded_lock:
            _recorded.append({"name": name, "start": float(start),
                              "end": float(end), "args": dict(args)})
    except Exception:
        pass


def recorded_spans(name: Optional[str] = None,
                   clear: bool = False) -> List[dict]:
    """Snapshot recorded spans (optionally filtered by name), oldest first."""
    with _recorded_lock:
        spans = list(_recorded)
        if clear:
            _recorded.clear()
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    return spans


def clear_recorded_spans() -> None:
    with _recorded_lock:
        _recorded.clear()


def chrome_tracing_dump(task_events: List[dict],
                        filename: Optional[str] = None,
                        include_recorded: bool = False) -> List[dict]:
    """Convert the state API's task list into chrome://tracing events.

    ``include_recorded=True`` appends the in-process span recorder's
    entries as a separate thread lane ("spans"), so pipeline dispatch/drain
    overlap shows up against the task timeline."""
    events = []
    for t in task_events:
        if t.get("start") is None or t.get("end") is None:
            continue
        events.append({
            "name": t["name"],
            "cat": t.get("type", "TASK"),
            "ph": "X",  # complete event
            "ts": t["start"] * 1e6,
            "dur": (t["end"] - t["start"]) * 1e6,
            "pid": "ray_tpu",
            "tid": (t.get("worker_id") or "driver")[:12],
            "args": {"task_id": t["task_id"], "attempt": t.get("attempt", 0),
                     "status": t.get("status")},
        })
    if include_recorded:
        for s in recorded_spans():
            events.append({
                "name": s["name"],
                "cat": "SPAN",
                "ph": "X",
                "ts": s["start"] * 1e6,
                "dur": (s["end"] - s["start"]) * 1e6,
                "pid": "ray_tpu",
                "tid": "spans",
                "args": s["args"],
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
