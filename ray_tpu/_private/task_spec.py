"""Task/actor specifications and common enums.

Equivalent of the reference's TaskSpecification (src/ray/common/task/
task_spec.h, protobuf common.proto TaskSpec) — a plain dataclass here since
the wire is in-cluster pickle; a protobuf schema can replace it when the
head moves out of process.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)


# Metadata prefix marking an inline object as a serialized error result
# (reference: RAY_ERROR metadata in plasma objects).
ERROR_META = b"__rtpu_error__"


class TaskType(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2
    DRIVER = 3


class ArgKind(enum.Enum):
    VALUE = 0  # serialized inline value
    REF = 1  # ObjectID to resolve before execution


@dataclass
class TaskArg:
    kind: ArgKind
    value: Any = None  # (metadata, data) bytes for VALUE
    ref: Optional[ObjectID] = None
    # ObjectIDs nested inside a VALUE arg (e.g. a list of refs): pinned for
    # the task's lifetime like direct ref args (borrow protocol,
    # reference: contained_ids in src/ray/core_worker/reference_count.h).
    contained: List[ObjectID] = field(default_factory=list)
    # Owner address for REF args held in a caller's in-process store
    # (reference: owner_address in TaskArg, common.proto) — the executing
    # worker fetches the bytes from the owner, not the head.
    owner: Optional[dict] = None
    # oid-binary -> owner address for `contained` refs (same role).
    contained_owners: Optional[Dict[bytes, dict]] = None

    def __reduce__(self):
        return (_rebuild_arg, (self.kind.value, self.value, self.ref,
                               self.contained or None, self.owner,
                               self.contained_owners))


def _rebuild_arg(kind, value, ref, contained, owner, contained_owners):
    return TaskArg(ArgKind(kind), value, ref, contained or [], owner,
                   contained_owners)


@dataclass
class SchedulingStrategy:
    """Union of DEFAULT / SPREAD / node-affinity / placement-group strategies
    (reference: python/ray/util/scheduling_strategies.py)."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False

    def __reduce__(self):
        # Compact wire form: specs cross a process boundary per task on the
        # hot path; the default dataclass pickle (class + field dict) costs
        # several x this tuple form.
        if self.kind == "DEFAULT" and self.node_id is None:
            return (_default_strategy, ())
        return (SchedulingStrategy,
                (self.kind, self.node_id, self.soft,
                 self.placement_group_id, self.bundle_index,
                 self.capture_child_tasks))


def _default_strategy() -> SchedulingStrategy:
    return SchedulingStrategy()


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    name: str
    # Function payload: cloudpickle blob + stable hash for caching, or for
    # actor tasks the method name resolved against the actor instance.
    func_blob: Optional[bytes] = None
    func_hash: Optional[bytes] = None
    method_name: Optional[str] = None
    args: List[TaskArg] = field(default_factory=list)
    kwargs: Dict[str, TaskArg] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    # Default max_retries for this actor's method calls (creation spec only;
    # reference: max_task_retries, src/ray/core_worker/task_manager.h —
    # actor tasks replay across restarts up to this many times).
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    actor_method_names: List[str] = field(default_factory=list)
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    runtime_env: Optional[dict] = None
    # Ownership / lineage
    owner_worker_id: Optional[WorkerID] = None
    parent_task_id: Optional[TaskID] = None
    # Bookkeeping filled in by the scheduler
    attempt: int = 0
    # Distributed trace context carried from the submitting thread:
    # (trace_id, parent_span_id) hex pair — see ray_tpu.observability.
    trace_ctx: Optional[Tuple[str, str]] = None

    def return_ids(self) -> List[ObjectID]:
        ids = getattr(self, "_return_ids", None)
        if ids is None:
            ids = [ObjectID.for_task_return(self.task_id, i)
                   for i in range(self.num_returns)]
            self._return_ids = ids
        return ids

    def scheduling_class(self) -> Tuple:
        """Key for lease reuse: same-shaped tasks share leased workers
        (reference: SchedulingClass in src/ray/common/task/task_spec.h).
        Cached — it's recomputed on every pending-queue drain pass."""
        key = getattr(self, "_sched_class", None)
        if key is None:
            key = (tuple(sorted(self.resources.items())),
                   self.runtime_env is None)
            self._sched_class = key
        return key

    def __reduce__(self):
        return (_rebuild_spec, (
            self.task_id, self.job_id, self.task_type.value, self.name,
            self.func_blob, self.func_hash, self.method_name,
            self.args or None, self.kwargs or None, self.num_returns,
            self.resources or None, self.scheduling_strategy,
            self.max_retries, self.retry_exceptions, self.actor_id,
            self.max_restarts, self.max_task_retries, self.max_concurrency,
            self.actor_name, self.actor_method_names or None,
            self.namespace, self.lifetime, self.runtime_env,
            self.owner_worker_id, self.parent_task_id, self.attempt,
            self.trace_ctx))


def _rebuild_spec(task_id, job_id, task_type, name, func_blob, func_hash,
                  method_name, args, kwargs, num_returns, resources,
                  scheduling_strategy, max_retries, retry_exceptions,
                  actor_id, max_restarts, max_task_retries, max_concurrency,
                  actor_name, actor_method_names, namespace, lifetime,
                  runtime_env, owner_worker_id, parent_task_id, attempt,
                  trace_ctx=None):
    return TaskSpec(task_id, job_id, TaskType(task_type), name, func_blob,
                    func_hash, method_name, args or [], kwargs or {},
                    num_returns, resources or {}, scheduling_strategy,
                    max_retries, retry_exceptions, actor_id, max_restarts,
                    max_task_retries, max_concurrency, actor_name,
                    actor_method_names or [], namespace, lifetime,
                    runtime_env, owner_worker_id, parent_task_id, attempt,
                    trace_ctx)


@dataclass
class TaskResult:
    object_id: ObjectID
    inline: Optional[Tuple[bytes, bytes]] = None  # (metadata, data) for small objects
    in_store: bool = False
    size: int = 0
    meta: bytes = b""
    # Refs nested in an inline result: [(oid binary, owner addr)].  The
    # returner holds a `ret:` pin on each at its owner; the caller takes
    # over with a `res:` pin tied to the result entry's lifetime, then
    # releases the returner's pin (reference: contained-ref handover in
    # task replies, reference_count.h:543).
    contained: Optional[List[Tuple[bytes, dict]]] = None

    def __reduce__(self):
        return (TaskResult, (self.object_id, self.inline, self.in_store,
                             self.size, self.meta, self.contained))


class TaskStatus(enum.Enum):
    PENDING = 0
    SCHEDULED = 1
    RUNNING = 2
    FINISHED = 3
    FAILED = 4
