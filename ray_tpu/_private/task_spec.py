"""Task/actor specifications and common enums.

Equivalent of the reference's TaskSpecification (src/ray/common/task/
task_spec.h, protobuf common.proto TaskSpec) — a plain dataclass here since
the wire is in-cluster pickle; a protobuf schema can replace it when the
head moves out of process.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)


class TaskType(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2
    DRIVER = 3


class ArgKind(enum.Enum):
    VALUE = 0  # serialized inline value
    REF = 1  # ObjectID to resolve before execution


@dataclass
class TaskArg:
    kind: ArgKind
    value: Any = None  # (metadata, data) bytes for VALUE
    ref: Optional[ObjectID] = None
    # ObjectIDs nested inside a VALUE arg (e.g. a list of refs): pinned for
    # the task's lifetime like direct ref args (borrow protocol,
    # reference: contained_ids in src/ray/core_worker/reference_count.h).
    contained: List[ObjectID] = field(default_factory=list)


@dataclass
class SchedulingStrategy:
    """Union of DEFAULT / SPREAD / node-affinity / placement-group strategies
    (reference: python/ray/util/scheduling_strategies.py)."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    name: str
    # Function payload: cloudpickle blob + stable hash for caching, or for
    # actor tasks the method name resolved against the actor instance.
    func_blob: Optional[bytes] = None
    func_hash: Optional[bytes] = None
    method_name: Optional[str] = None
    args: List[TaskArg] = field(default_factory=list)
    kwargs: Dict[str, TaskArg] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    # Default max_retries for this actor's method calls (creation spec only;
    # reference: max_task_retries, src/ray/core_worker/task_manager.h —
    # actor tasks replay across restarts up to this many times).
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    actor_method_names: List[str] = field(default_factory=list)
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    runtime_env: Optional[dict] = None
    # Ownership / lineage
    owner_worker_id: Optional[WorkerID] = None
    parent_task_id: Optional[TaskID] = None
    # Bookkeeping filled in by the scheduler
    attempt: int = 0

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def scheduling_class(self) -> Tuple:
        """Key for lease reuse: same-shaped tasks share leased workers
        (reference: SchedulingClass in src/ray/common/task/task_spec.h).
        Cached — it's recomputed on every pending-queue drain pass."""
        key = getattr(self, "_sched_class", None)
        if key is None:
            key = (tuple(sorted(self.resources.items())),
                   self.runtime_env is None)
            self._sched_class = key
        return key


@dataclass
class TaskResult:
    object_id: ObjectID
    inline: Optional[Tuple[bytes, bytes]] = None  # (metadata, data) for small objects
    in_store: bool = False
    size: int = 0
    meta: bytes = b""


class TaskStatus(enum.Enum):
    PENDING = 0
    SCHEDULED = 1
    RUNNING = 2
    FINISHED = 3
    FAILED = 4
