"""Shared RPC deadline/retry machinery for the control plane.

Reference: Ray treats every cross-process edge as lossy — per-call
deadlines with retries in the GCS/raylet clients (gcs_rpc_client.h
retryable grpc client, ray_config_def.h's *_rpc_timeout_ms family) are
what let it survive real clusters.  This module is the one place that
policy lives here:

- :class:`Deadline` — a monotonic budget threaded through retry loops.
- :class:`RetryPolicy` — exponential backoff with jitter, used both for
  resend cadence (attempt timeouts) and inter-attempt sleeps.
- :class:`ReplyCache` — the head-side exactly-once filter: every
  ``request`` frame carries an idempotency key; the first frame with a
  key executes (entry IN_PROGRESS -> DONE with the cached reply), any
  duplicate/retried frame *attaches* to the entry and is answered from
  the cache instead of re-applying the op.  This is what makes blind
  resends safe for non-idempotent ops (submit, seal, put_inline).
- The in-flight registry + :func:`rpc_inflight_stats` — a hung-call
  watchdog surface: every pending RPC's age is observable, and
  transports dump the blocked thread's stack to stderr once a call
  outlives its deadline (see ConnTransport's keeper thread).

Counters in :data:`RPC_STATS` are per-process and cheap (plain dict
increments under one lock); tests and the perf smoke assert on them.
"""
from __future__ import annotations

import random
import sys
import threading
import time
import traceback
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.exceptions import RpcTimeoutError  # noqa: F401 — re-export

# ---------------------------------------------------------------------------
# Deadlines + backoff
# ---------------------------------------------------------------------------


class Deadline:
    """A monotonic time budget.  ``timeout=None`` (or <= 0) = unbounded."""

    __slots__ = ("timeout", "start", "_until")

    def __init__(self, timeout: Optional[float]):
        if timeout is not None and timeout <= 0:
            timeout = None
        self.timeout = timeout
        self.start = time.monotonic()
        self._until = None if timeout is None else self.start + timeout

    def remaining(self) -> Optional[float]:
        if self._until is None:
            return None
        return self._until - time.monotonic()

    def expired(self) -> bool:
        return self._until is not None and time.monotonic() >= self._until

    def elapsed(self) -> float:
        return time.monotonic() - self.start

    def bound(self, interval: float) -> float:
        """Clamp a per-attempt wait to what's left of the budget."""
        rem = self.remaining()
        if rem is None:
            return interval
        return max(0.0, min(interval, rem))


class RetryPolicy:
    """Exponential backoff with jitter (reference: the gcs client's
    exponential-backoff reconnect, ray_config_def.h:58-62)."""

    __slots__ = ("base", "mult", "cap", "jitter", "_rng")

    def __init__(self, base: float = 0.05, mult: float = 2.0,
                 cap: float = 2.0, jitter: float = 0.2,
                 seed: Optional[int] = None):
        self.base = base
        self.mult = mult
        self.cap = cap
        self.jitter = jitter
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.cap, self.base * (self.mult ** max(0, attempt - 1)))
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)


def rpc_defaults() -> Tuple[Optional[float], float]:
    """(default overall timeout | None, per-attempt resend interval)."""
    from ray_tpu._private.config import CONFIG

    total = CONFIG.rpc_timeout
    return (total if total and total > 0 else None,
            max(0.01, CONFIG.rpc_attempt_timeout))


# ---------------------------------------------------------------------------
# Stats
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
RPC_STATS: Dict[str, int] = {
    "retries": 0,          # blocking-request resends
    "async_retries": 0,    # keeper-thread resends of acked one-way ops
    "timeouts": 0,         # RpcTimeoutError raised
    "async_dropped": 0,    # acked one-way ops abandoned past deadline
    "dedup_hits": 0,       # head reply-cache hits (duplicate frames)
    "hang_dumps": 0,       # watchdog stack dumps emitted
    "net_faults": 0,       # chaos faults actually injected
}


def note(counter: str, n: int = 1) -> None:
    with _stats_lock:
        RPC_STATS[counter] = RPC_STATS.get(counter, 0) + n


def rpc_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(RPC_STATS)


def reset_rpc_stats() -> None:
    with _stats_lock:
        for k in RPC_STATS:
            RPC_STATS[k] = 0


# ---------------------------------------------------------------------------
# Head-side exactly-once reply cache
# ---------------------------------------------------------------------------

class ReplyCache:
    """Idempotency-key -> reply memo with in-progress attachment.

    ``admit(key, reply)`` returns ``(should_run, wrapped_reply)``:

    - first frame for ``key``: ``(True, wrapped)`` — the caller runs the
      handler with ``wrapped``, which records the reply and flushes any
      duplicates that attached while the op was in flight;
    - duplicate frame: ``(False, None)`` — its ``reply`` was either
      answered immediately from the cache (op already done) or attached
      to the in-progress entry (answered when the first execution
      replies).  The op itself is never applied twice.

    Entries are bounded (``cap``) and aged out (``ttl`` seconds after
    their reply was recorded); in-progress entries are never evicted —
    a deferred reply (blocking get) may legitimately take minutes.
    """

    _DONE = 1
    _IN_PROGRESS = 0

    def __init__(self, cap: int = 1024, ttl: float = 300.0):
        self.cap = cap
        self.ttl = ttl
        self._lock = threading.Lock()
        # key -> [state, value, error, waiters, done_ts]
        self._entries: "OrderedDict[bytes, list]" = OrderedDict()

    def admit(self, key: bytes, reply: Callable
              ) -> Tuple[bool, Optional[Callable]]:
        replay = None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = [self._IN_PROGRESS, None, None,
                                          [], 0.0]
                self._prune_locked()

                def wrapped(value=None, error=None, _e=e):
                    with self._lock:
                        if _e[0] == self._DONE:
                            return  # handler double-reply: first wins
                        _e[0] = self._DONE
                        _e[1], _e[2] = value, error
                        _e[4] = time.monotonic()
                        waiters, _e[3] = _e[3], []
                    reply(value, error=error)
                    for w in waiters:
                        try:
                            w(value, error=error)
                        except Exception:
                            pass

                return True, wrapped
            note("dedup_hits")
            if e[0] == self._DONE:
                replay = (e[1], e[2])
            else:
                e[3].append(reply)
        if replay is not None:
            reply(replay[0], error=replay[1])
        return False, None

    def _prune_locked(self):
        # Only DONE entries are evictable (an in-progress entry is a live
        # deferred reply); scan is bounded so admit() stays O(1)-ish.
        now = time.monotonic()
        over = len(self._entries) - self.cap
        scanned = 0
        for key in list(self._entries):
            scanned += 1
            if scanned > 256 or (over <= 0 and scanned > 32):
                break
            e = self._entries[key]
            if e[0] != self._DONE:
                continue
            if over > 0 or now - e[4] > self.ttl:
                del self._entries[key]
                over -= 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# In-flight registry (hung-call watchdog surface)
# ---------------------------------------------------------------------------

_transports: "weakref.WeakSet" = weakref.WeakSet()
_transports_lock = threading.Lock()


def register_transport(transport) -> None:
    """Transports with a ``pending_rpcs()`` accessor register here so the
    process-wide in-flight stats cover every connection."""
    with _transports_lock:
        _transports.add(transport)


def rpc_inflight_stats() -> Dict[str, Any]:
    """Snapshot of every in-flight RPC in this process: count, max age,
    and the oldest op — the watchdog's exported metric surface."""
    now = time.monotonic()
    count = 0
    max_age = 0.0
    oldest_op = None
    with _transports_lock:
        transports = list(_transports)
    for tr in transports:
        try:
            pending = tr.pending_rpcs()
        except Exception:
            continue
        for rec in pending:
            count += 1
            age = now - rec.started
            if age >= max_age:
                max_age = age
                oldest_op = rec.op
    return {"count": count, "max_age_s": max_age, "oldest_op": oldest_op}


def dump_blocked_rpc(rec, reason: str = "past deadline") -> None:
    """Stderr dump for a stuck call: op, age, attempts, and the waiting
    thread's stack (the in-process SIGUSR1 equivalent, per call)."""
    note("hang_dumps")
    age = time.monotonic() - rec.started
    lines = [f"[ray_tpu rpc-watchdog] RPC {rec.op!r} {reason}: "
             f"age {age:.1f}s, {rec.attempts} attempt(s), "
             f"mode={rec.mode}"]
    frame = sys._current_frames().get(getattr(rec, "thread_id", None) or -1)
    if frame is not None:
        lines.append("".join(traceback.format_stack(frame)))
    sys.stderr.write("\n".join(lines) + "\n")
