import os


def inject_pkg_pythonpath(env: dict) -> dict:
    """Prepend the ray_tpu package parent to env['PYTHONPATH'] so spawned
    subprocesses (workers, node-agent workers, job entrypoints) can import
    ray_tpu even when the driver runs from a source tree rather than an
    installed package.  Skips empty segments — a trailing ':' would put the
    subprocess cwd on sys.path and shadow stdlib modules."""
    import ray_tpu as _pkg

    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(_pkg.__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_parent, env.get("PYTHONPATH")) if p)
    return env
