"""Cluster-recovery counters: the observable surface of the node-loss plane.

Reference: Ray's fault-tolerance story (arxiv 1712.05889) is lineage
reconstruction plus surviving whole-node loss; the operator-facing proof
that recovery *happened* (rather than silently degraded results) is a
counter surface — the reference exports object_manager/reconstruction
metrics through the reporter agent.  Same pattern as the RPC plane's
``retry.RPC_STATS``: per-process plain-dict increments under one lock,
asserted on by chaos tests and merged into the head node's stats snapshot
(``node_stats`` → GCS node table → dashboard ``/metrics`` gauges).

Counters:

- ``node_deaths``            — nodes the head declared dead (exactly once
  per node: conn EOF, lease expiry, or explicit kill).
- ``objects_lost``           — objects whose last copy died with a node and
  that had NO recovery path (callers see ``ObjectLostError``).
- ``objects_reconstructed``  — lineage reconstructions resubmitted for
  task outputs lost with a node/eviction.
- ``objects_replicated``     — durable-put replicas written by the
  ``object_durability=replicate:K`` plane.
- ``objects_restored``       — objects that survived a holder-node death
  through a surviving replica location or a spill-file restore.
- ``oom_worker_kills``       — workers killed by a memory monitor (head or
  node agent) whose death surfaced as a typed ``OutOfMemoryError`` mark.
"""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()

RECOVERY_STATS: Dict[str, int] = {
    "node_deaths": 0,
    "objects_lost": 0,
    "objects_reconstructed": 0,
    "objects_replicated": 0,
    "objects_restored": 0,
    "oom_worker_kills": 0,
}


def note(counter: str, n: int = 1) -> None:
    with _lock:
        RECOVERY_STATS[counter] = RECOVERY_STATS.get(counter, 0) + n


def recovery_stats() -> Dict[str, int]:
    with _lock:
        return dict(RECOVERY_STATS)


def reset_recovery_stats() -> None:
    with _lock:
        for k in RECOVERY_STATS:
            RECOVERY_STATS[k] = 0
