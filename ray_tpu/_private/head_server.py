"""Standalone head process: ``python -m ray_tpu._private.head_server``.

The failover topology (reference: a GCS process separate from drivers,
src/ray/gcs/gcs_server/gcs_server_main.cc): the head runs alone with a
FIXED tcp port and a session dir holding its durable identity (authkey)
and GCS snapshot; agents, workers and drivers connect over TCP and
survive a head restart by reconnecting (see node_agent/default_worker/
driver_client reconnect loops).
"""
from __future__ import annotations

import argparse
import signal
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--session-dir", required=True)
    p.add_argument("--num-cpus", type=float, default=0.0,
                   help="resources for an optional head-local node "
                        "(0 = head is control-plane only)")
    p.add_argument("--snapshot-period", type=float, default=1.0)
    args = p.parse_args()

    from ray_tpu._private.config import CONFIG

    # A standalone head snapshots continuously by default — failover
    # restores from the last snapshot (overridable via env/_system_config).
    import os

    if "RAY_TPU_GCS_SNAPSHOT_PERIOD_S" not in os.environ:
        CONFIG.apply_system_config(
            {"gcs_snapshot_period_s": args.snapshot_period})

    from ray_tpu._private.head import Head

    head = Head(session_dir=args.session_dir, tcp_port=args.port)
    if args.num_cpus > 0:
        head.add_node({"CPU": args.num_cpus})
    print(f"head up: {head.tcp_address} session={head.session_dir}",
          flush=True)

    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.2)
    head.gcs.save_snapshot(head.gcs_snapshot_path)
    head.shutdown()


if __name__ == "__main__":
    main()
