"""Global Control Service: cluster metadata, actor directory, KV, pubsub,
object directory + distributed reference counting, placement groups, jobs.

TPU-native re-architecture of the reference's GCS server
(src/ray/gcs/gcs_server/gcs_server.h:77) and of the owner-side reference
counter (src/ray/core_worker/reference_count.h:61).  Two deliberate
divergences, both motivated by the target topology (one controller host plus
gang-scheduled TPU-host worker processes, not a 250-node heterogeneous
cluster):

1. The GCS runs *in the head process* behind thread-safe method calls rather
   than as a separate gRPC server.  The interface is kept message-shaped so it
   can be moved out-of-process (or to C++) without touching callers.
2. Reference counting is owner-centralized: every process keeps local
   refcounts and reports add/remove of its *root* references to the GCS,
   which holds the authoritative holder-set per object.  This trades the
   reference's fully distributed borrowing protocol for a much smaller state
   machine; lineage release and store eviction key off the same holder-set.

Storage is pluggable like the reference's StoreClient
(src/ray/gcs/store_client/store_client.h:33): in-memory default, with a
file-backed snapshot for GCS restart (redis equivalent) later.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from ray_tpu._private.task_spec import TaskSpec, TaskStatus


class ActorState:
    DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
    PENDING_CREATION = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class ActorInfo:
    """Actor lifecycle record (reference FSM: gcs_actor_manager.h:280)."""

    __slots__ = (
        "actor_id", "name", "namespace", "state", "creation_spec", "node_id",
        "worker_id", "num_restarts", "max_restarts", "death_cause", "lifetime",
        "reconnect_worker_id",
        "class_name", "pending_calls", "resources_held",
    )

    def __init__(self, actor_id: ActorID, creation_spec: TaskSpec):
        self.actor_id = actor_id
        self.name = creation_spec.actor_name
        self.namespace = creation_spec.namespace or "default"
        self.state = ActorState.PENDING_CREATION
        self.creation_spec = creation_spec
        self.node_id: Optional[NodeID] = None
        self.worker_id: Optional[WorkerID] = None
        self.num_restarts = 0
        self.max_restarts = creation_spec.max_restarts
        self.death_cause: Optional[str] = None
        self.lifetime = creation_spec.lifetime
        self.class_name = creation_spec.name.replace(".__init__", "")
        # Set on snapshot-restore: the worker id this actor ran on before
        # the head died; a re-registering worker with this id re-adopts
        # the actor (head failover, see head._on_register).
        self.reconnect_worker_id = None
        self.pending_calls: List[TaskSpec] = []
        # True while the creation-task resources are allocated on a node;
        # guards against double-release on kill + worker-death paths.
        self.resources_held = False


class NodeInfo:
    __slots__ = ("node_id", "resources", "alive", "labels", "address",
                 "last_heartbeat", "stats")

    def __init__(self, node_id: NodeID, resources: Dict[str, float], labels=None):
        self.node_id = node_id
        self.resources = dict(resources)
        self.alive = True
        self.labels = labels or {}
        self.address = None
        self.last_heartbeat = time.monotonic()
        self.stats: Dict[str, float] = {}  # cpu/mem/store usage snapshot


class ObjectEntry:
    """Object directory + refcount record (owner-side state)."""

    __slots__ = (
        "object_id", "locations", "inline", "holders", "lineage_task",
        "size", "meta", "spilled_path", "lost", "segments",
        "spill", "spill_host", "contained", "partials",
    )

    def __init__(self, object_id: ObjectID):
        self.object_id = object_id
        self.locations: Set[NodeID] = set()
        self.inline: Optional[Tuple[bytes, bytes]] = None  # (meta, data) small objects
        self.holders: Set[bytes] = set()  # worker ids holding a root reference
        self.lineage_task: Optional[TaskID] = None
        self.size = 0
        # Serialization metadata, kept directory-side for objects whose store
        # lives in another process/host (cross-host pull resolutions need it).
        self.meta: Optional[bytes] = None
        self.spilled_path: Optional[str] = None
        self.lost = False
        # Non-canonical shm segment name PER LOCATION (pooled segments,
        # replica segments); a node absent here means readers on its host
        # derive the name from the object id.  Per-node because a replica
        # never shares the primary's segment name.
        self.segments: Dict[NodeID, str] = {}
        # Directory-side spill record: (path, meta, size) of an on-disk
        # copy that outlives its store (eager durability backup or an
        # eviction spill the head was told about).  ``spill_host`` is the
        # host key owning the file; None = the head's own host — the form
        # that stays valid across a head restart (host keys are per-
        # process-random, the head host is not).
        self.spill: Optional[Tuple[str, bytes, int]] = None
        self.spill_host: Optional[str] = None
        # Head-counted refs nested inside this object's value: each holds
        # a ``res:<this id>`` holder ref for as long as THIS entry lives,
        # released (cascading) when it is freed — a nested object must
        # never die while something can still reach it through the outer
        # ref (reference: contained-ref handover, reference_count.h:543).
        self.contained: Optional[List[ObjectID]] = None
        # Cooperative-broadcast partial holders: sender key (worker id /
        # node key) -> {"addr", "chunk", "total", "chunks": set, "host"}.
        # A receiver mid-pull advertises the chunk ranges it has landed
        # so concurrent pullers stripe off it instead of the owner; the
        # record dies with its process (or on its drop notify).  None
        # until the first advertisement — most objects never have one.
        self.partials: Optional[Dict[bytes, dict]] = None


class TaskEvent:
    __slots__ = ("task_id", "name", "status", "node_id", "worker_id", "start", "end",
                 "attempt", "error", "type", "parent_task_id", "trace_id")

    def __init__(self, task_id, name, status, **kw):
        self.task_id = task_id
        self.name = name
        self.status = status
        self.node_id = kw.get("node_id")
        self.worker_id = kw.get("worker_id")
        self.start = kw.get("start")
        self.end = kw.get("end")
        self.attempt = kw.get("attempt", 0)
        self.error = kw.get("error")
        self.type = kw.get("type", "NORMAL")
        self.parent_task_id = kw.get("parent_task_id")
        # Distributed trace this task belongs to (tracing plane).
        self.trace_id = kw.get("trace_id")


class GCS:
    """The cluster brain. All methods are thread-safe."""

    def __init__(self):
        self._lock = threading.RLock()
        # Tables (reference: gcs_table_storage.h typed tables)
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.objects: Dict[ObjectID, ObjectEntry] = {}
        self.kv: Dict[str, Dict[bytes, bytes]] = defaultdict(dict)
        self.task_events: Dict[TaskID, TaskEvent] = {}
        # Lineage: task specs kept while their outputs may need reconstruction
        # (reference: lineage in task_manager.h:90, max_lineage_bytes).
        self.lineage: Dict[TaskID, TaskSpec] = {}
        self.lineage_refcount: Dict[TaskID, int] = defaultdict(int)
        # Pubsub (reference: src/ray/pubsub) — in-process callback channels.
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = defaultdict(list)

    # ---------------- persistence ----------------
    def snapshot(self) -> dict:
        """Durable cluster state (reference: gcs_table_storage.h over
        RedisStoreClient, redis_store_client.h:28 — here a picklable dict
        written to the session dir).  Scope: the tables that outlive
        processes — KV (function/class exports, workflow state), jobs, and
        detached-actor name registrations; live sockets/workers/objects are
        process state and rebuild on restart."""
        with self._lock:
            actors = {}
            for aid, info in self.actors.items():
                if info.state == ActorState.DEAD:
                    continue
                actors[aid] = {
                    "creation_spec": info.creation_spec,
                    "worker_id": (info.worker_id.binary()
                                  if info.worker_id else None),
                    "num_restarts": info.num_restarts,
                }
            # Durable spill records: on-disk object copies outlive both
            # their store AND the head process — a restarted head must be
            # able to serve restores for them (spill-record survival
            # across head kill9, the node-loss durability contract).
            # Only head-host records (spill_host None) persist: a remote
            # host's files are reachable only through its agent, which
            # re-registers and re-reports its own spill state.
            spills = {}
            for oid, e in self.objects.items():
                if e.spill is not None and e.spill_host is None:
                    spills[oid.binary()] = {
                        "path": e.spill[0], "meta": e.spill[1],
                        "size": e.spill[2]}
            return {
                "kv": {ns: dict(t) for ns, t in self.kv.items()},
                "jobs": dict(self.jobs),
                "named_actors": dict(self.named_actors),
                "actors": actors,
                "object_spills": spills,
            }

    def restore(self, snap: dict):
        from ray_tpu._private.ids import WorkerID as _WorkerID

        with self._lock:
            for ns, t in snap.get("kv", {}).items():
                self.kv[ns].update(t)
            self.jobs.update(snap.get("jobs", {}))
            # Actors: restore live records as RESTARTING and remember the
            # worker each ran on — its (still-running) worker process
            # re-registers after a head restart and re-adopts the actor
            # with its state intact (head failover; reference: GCS FT over
            # redis_store_client.h + worker reconnect,
            # ray_config_def.h:58-62).  Workers that never come back are
            # reaped by the head's reconnect-window timer.
            for aid, rec in snap.get("actors", {}).items():
                if aid in self.actors:
                    continue
                info = ActorInfo(aid, rec["creation_spec"])
                info.state = ActorState.RESTARTING
                info.num_restarts = rec.get("num_restarts", 0)
                if rec.get("worker_id"):
                    info.reconnect_worker_id = _WorkerID(rec["worker_id"])
                self.actors[aid] = info
            for key, actor_id in snap.get("named_actors", {}).items():
                if actor_id in self.actors:
                    self.named_actors.setdefault(key, actor_id)
            import os as _os

            from ray_tpu._private.ids import ObjectID as _ObjectID

            for oid_bin, rec in snap.get("object_spills", {}).items():
                if not _os.path.exists(rec["path"]):
                    continue  # the file died with the old session dir
                e = self._entry(_ObjectID(oid_bin))
                e.spill = (rec["path"], rec["meta"], rec["size"])
                e.spill_host = None
                e.meta = e.meta or rec["meta"]
                e.size = e.size or rec["size"]

    def save_snapshot(self, path: str):
        import os
        import pickle

        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self.snapshot(), f)
        os.replace(tmp, path)  # atomic: readers never see a torn file

    def load_snapshot(self, path: str) -> bool:
        import pickle

        try:
            with open(path, "rb") as f:
                self.restore(pickle.load(f))
            return True
        except (FileNotFoundError, EOFError, pickle.UnpicklingError):
            return False

    # ---------------- pubsub ----------------
    def subscribe(self, channel: str, callback: Callable[[Any], None]):
        with self._lock:
            self._subscribers[channel].append(callback)

    def publish(self, channel: str, message: Any):
        with self._lock:
            subs = list(self._subscribers.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass

    # ---------------- nodes ----------------
    def register_node(self, info: NodeInfo):
        with self._lock:
            self.nodes[info.node_id] = info
        self.publish("NODE", ("ALIVE", info.node_id))

    def remove_node(self, node_id: NodeID):
        with self._lock:
            info = self.nodes.get(node_id)
            if info:
                info.alive = False
        self.publish("NODE", ("DEAD", node_id))

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # ---------------- jobs ----------------
    def get_job_config(self, job_id: JobID) -> dict:
        with self._lock:
            info = self.jobs.get(job_id)
            return dict((info or {}).get("config") or {})

    def add_job(self, job_id: JobID, config: dict):
        with self._lock:
            self.jobs[job_id] = {"job_id": job_id, "config": config,
                                 "start_time": time.time(), "status": "RUNNING"}

    def finish_job(self, job_id: JobID):
        with self._lock:
            if job_id in self.jobs:
                self.jobs[job_id]["status"] = "FINISHED"
                self.jobs[job_id]["end_time"] = time.time()

    # ---------------- KV (internal_kv) ----------------
    def kv_put(self, key: bytes, value: bytes, namespace: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            ns = self.kv[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
            return True

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self.kv[namespace].get(key)

    def kv_del(self, key: bytes, namespace: str = "default"):
        with self._lock:
            self.kv[namespace].pop(key, None)

    def kv_keys(self, prefix: bytes, namespace: str = "default") -> List[bytes]:
        with self._lock:
            return [k for k in self.kv[namespace] if k.startswith(prefix)]

    # ---------------- actors ----------------
    def register_actor(self, spec: TaskSpec) -> ActorInfo:
        with self._lock:
            info = ActorInfo(spec.actor_id, spec)
            self.actors[spec.actor_id] = info
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(f"actor name {info.name!r} already taken")
                self.named_actors[key] = spec.actor_id
            return info

    def actor_started(self, actor_id: ActorID, node_id: NodeID, worker_id: WorkerID):
        with self._lock:
            info = self.actors[actor_id]
            info.state = ActorState.ALIVE
            info.node_id = node_id
            info.worker_id = worker_id
        self.publish("ACTOR", ("ALIVE", actor_id))

    def actor_failed(self, actor_id: ActorID, cause: str) -> str:
        """Returns the new state: RESTARTING (caller should reschedule) or DEAD."""
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return ActorState.DEAD
            restartable = (info.max_restarts == -1
                           or info.num_restarts < info.max_restarts)
            if restartable:
                info.num_restarts += 1
                info.state = ActorState.RESTARTING
                info.node_id = info.worker_id = None
            else:
                info.state = ActorState.DEAD
                info.death_cause = cause
                if info.name:
                    self.named_actors.pop((info.namespace, info.name), None)
            state = info.state
        self.publish("ACTOR", (state, actor_id))
        return state

    def kill_actor(self, actor_id: ActorID):
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None:
                return
            info.state = ActorState.DEAD
            info.death_cause = "killed via kill()"
            if info.name:
                self.named_actors.pop((info.namespace, info.name), None)
        self.publish("ACTOR", (ActorState.DEAD, actor_id))

    def get_actor_info(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> Optional[ActorID]:
        with self._lock:
            return self.named_actors.get((namespace, name))

    def list_named_actors(self, all_namespaces: bool = False) -> List[dict]:
        with self._lock:
            return [{"namespace": ns, "name": n} for (ns, n) in self.named_actors]

    # ---------------- object directory + refcounting ----------------
    def _entry(self, oid: ObjectID) -> ObjectEntry:
        e = self.objects.get(oid)
        if e is None:
            e = self.objects[oid] = ObjectEntry(oid)
        return e

    def object_sealed(self, oid: ObjectID, node_id: NodeID, size: int,
                      lineage_task: Optional[TaskID] = None,
                      meta: Optional[bytes] = None,
                      segment: Optional[str] = None):
        with self._lock:
            e = self._entry(oid)
            e.locations.add(node_id)
            e.size = size
            e.lost = False
            if meta is not None:
                e.meta = meta
            if segment is not None:
                e.segments[node_id] = segment
            if lineage_task is not None:
                e.lineage_task = lineage_task

    def object_inline(self, oid: ObjectID, meta: bytes, data: bytes,
                      lineage_task: Optional[TaskID] = None):
        with self._lock:
            e = self._entry(oid)
            e.inline = (meta, data)
            e.size = len(data)
            e.lost = False
            if lineage_task is not None:
                e.lineage_task = lineage_task

    def object_spill_recorded(self, oid: ObjectID, path: str, meta: bytes,
                              size: int, host: Optional[str] = None):
        """Record a directory-side spill/backup copy: the bytes live at
        ``path`` on ``host`` (None = the head host) and survive the owning
        store's death.  The restore path is head._try_reconstruct."""
        with self._lock:
            e = self._entry(oid)
            e.spill = (path, meta, size)
            e.spill_host = host
            if meta is not None and e.meta is None:
                e.meta = meta
            if size and not e.size:
                e.size = size

    def object_lookup(self, oid: ObjectID) -> Optional[ObjectEntry]:
        with self._lock:
            return self.objects.get(oid)

    def add_reference(self, oid: ObjectID, holder: bytes):
        with self._lock:
            self._entry(oid).holders.add(holder)

    def remove_reference(self, oid: ObjectID, holder: bytes) -> bool:
        """Returns True when the object has no more holders (safe to free)."""
        with self._lock:
            e = self.objects.get(oid)
            if e is None:
                return True
            e.holders.discard(holder)
            return not e.holders

    def remove_all_references(self, holder: bytes) -> List[ObjectID]:
        """Worker/driver died: drop all its references. Returns freed ids."""
        with self._lock:
            freed = []
            for oid, e in self.objects.items():
                if holder in e.holders:
                    e.holders.discard(holder)
                    if not e.holders:
                        freed.append(oid)
            return freed

    def free_object(self, oid: ObjectID):
        with self._lock:
            e = self.objects.pop(oid, None)
            if e is not None and e.lineage_task is not None:
                self._release_lineage(e.lineage_task)

    # ---------------- lineage ----------------
    def record_lineage(self, spec: TaskSpec):
        with self._lock:
            self.lineage[spec.task_id] = spec
            self.lineage_refcount[spec.task_id] = spec.num_returns

    def get_lineage(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            return self.lineage.get(task_id)

    def _release_lineage(self, task_id: TaskID):
        n = self.lineage_refcount.get(task_id)
        if n is None:
            return
        n -= 1
        if n <= 0:
            self.lineage.pop(task_id, None)
            self.lineage_refcount.pop(task_id, None)
        else:
            self.lineage_refcount[task_id] = n

    # ---------------- task events (observability) ----------------
    def record_task_event(self, ev: TaskEvent):
        with self._lock:
            self.task_events[ev.task_id] = ev

    def update_task_status(self, task_id: TaskID, status: TaskStatus, **kw):
        with self._lock:
            ev = self.task_events.get(task_id)
            if ev is not None:
                ev.status = status
                for k, v in kw.items():
                    setattr(ev, k, v)

    # ---------------- state API backing ----------------
    def list_actors(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "actor_id": a.actor_id.hex(),
                    "class_name": a.class_name,
                    "state": a.state,
                    "name": a.name,
                    "num_restarts": a.num_restarts,
                    "node_id": a.node_id.hex() if a.node_id else None,
                }
                for a in self.actors.values()
            ]

    def touch_node(self, node_id: NodeID):
        """Refresh a node's liveness lease (any agent traffic counts)."""
        with self._lock:
            info = self.nodes.get(node_id)
            if info is not None:
                info.last_heartbeat = time.monotonic()

    def update_node_stats(self, node_id: NodeID, stats: dict):
        """Per-node usage snapshot from the monitor loop / node agent
        (reference: the reporter agent feeding the dashboard)."""
        with self._lock:
            info = self.nodes.get(node_id)
            if info is not None:
                info.stats = dict(stats)
                info.last_heartbeat = time.monotonic()

    def list_nodes(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "node_id": n.node_id.hex(),
                    "alive": n.alive,
                    "resources": dict(n.resources),
                    "labels": dict(n.labels),
                    "stats": dict(n.stats),
                }
                for n in self.nodes.values()
            ]

    def list_tasks(self) -> List[dict]:
        with self._lock:
            out = []
            for t in self.task_events.values():
                status = t.status.name if hasattr(t.status, "name") \
                    else str(t.status)
                out.append({
                    "task_id": t.task_id.hex(),
                    "name": t.name,
                    "status": status,
                    # "state" aliases "status" to match the reference's
                    # state API column naming.
                    "state": status,
                    "attempt": t.attempt,
                    "type": t.type,
                    "error": t.error,
                    "start": t.start,
                    "end": t.end,
                    "duration": (t.end - t.start)
                    if t.start is not None and t.end is not None else None,
                    "worker_id": t.worker_id.hex() if t.worker_id else None,
                    "node_id": t.node_id.hex() if t.node_id else None,
                    "parent_task_id": t.parent_task_id.hex()
                    if t.parent_task_id else None,
                    "trace_id": t.trace_id,
                })
            return out

    @staticmethod
    def _object_state(o: ObjectEntry) -> str:
        if o.lost:
            return "LOST"
        if o.inline is not None:
            return "INLINE"
        if o.locations:
            return "SEALED"
        if o.spill is not None or o.spilled_path is not None:
            return "SPILLED"
        return "PENDING"

    def list_objects(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "object_id": o.object_id.hex(),
                    "size": o.size,
                    "locations": [n.hex() for n in o.locations],
                    "inline": o.inline is not None,
                    "num_holders": len(o.holders),
                    "state": self._object_state(o),
                    "node_id": next(iter(o.locations)).hex()
                    if o.locations else None,
                }
                for o in self.objects.values()
            ]

    def list_jobs(self) -> List[dict]:
        with self._lock:
            return [
                {"job_id": j["job_id"].hex(), "status": j["status"]}
                for j in self.jobs.values()
            ]
