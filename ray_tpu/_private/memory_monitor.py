"""Host memory-pressure monitor + OOM worker-killing policies.

Equivalent of the reference's ``MemoryMonitor``
(src/ray/common/memory_monitor.h:52) and the raylet's pluggable
``WorkerKillingPolicy`` (src/ray/raylet/worker_killing_policy.h:33,
group-by-owner variant worker_killing_policy_group_by_owner.h:85): when
host (or cgroup) memory usage crosses a threshold, the node kills a
carefully-chosen worker instead of letting the kernel OOM-killer take
down the raylet/head — the victim's task is retried if it has retry
budget, else failed with :class:`~ray_tpu.exceptions.OutOfMemoryError`.

Policy choice (``worker_killing_policy`` flag):

- ``retriable_lifo`` (default, matching the reference's default,
  ray_config_def.h:103): newest retriable task first, then newest
  non-retriable (LIFO preserves the most accumulated work).
- ``group_by_owner``: group running workers by the owner that submitted
  their task; prefer the group with retriable tasks and the most members
  (killing there frees memory while leaving every owner some forward
  progress), newest task first within the group.

Usage is read from cgroup v2 (``memory.current``/``memory.max``) when
the process is inside a limited cgroup, else from ``/proc/meminfo``
(1 - MemAvailable/MemTotal) — the same dual sourcing as the reference's
``GetMemoryBytes``.  Tests inject pressure through the
``memory_monitor_test_file`` flag (a file holding a float fraction),
mirroring the reference's fake-memory test hook.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

_CGROUP_CURRENT = "/sys/fs/cgroup/memory.current"
_CGROUP_MAX = "/sys/fs/cgroup/memory.max"
_MEMINFO = "/proc/meminfo"


def host_memory_usage_fraction() -> float:
    """Fraction of memory in use on this host (0.0–1.0), preferring the
    cgroup v2 limit when one is set (containerized runs)."""
    try:
        with open(_CGROUP_MAX) as f:
            raw = f.read().strip()
        if raw != "max":
            limit = float(raw)
            with open(_CGROUP_CURRENT) as f:
                current = float(f.read().strip())
            if limit > 0:
                return current / limit
    except (OSError, ValueError):
        pass
    try:
        total = avail = None
        with open(_MEMINFO) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1])
                if total is not None and avail is not None:
                    break
        if total and avail is not None:
            # Fail open when MemAvailable is missing (pre-3.14 kernels /
            # restricted /proc): a fabricated 100% would kill-storm.
            return 1.0 - avail / total
    except (OSError, ValueError):
        pass
    return 0.0


# ---------------------------------------------------------------------------
# Worker-killing policies.  A candidate is (handle, spec, started_at) for a
# worker currently executing a task; both policies return the victim handle
# or None.  Pure functions over the snapshot so they unit-test in isolation
# (the reference's policies are tested the same way,
# worker_killing_policy_test.cc).
# ---------------------------------------------------------------------------
Candidate = Tuple[object, object, float]  # (WorkerHandle, TaskSpec, start time)


def _retriable(spec) -> bool:
    return spec.attempt < spec.max_retries


def retriable_lifo_policy(candidates: List[Candidate]) -> Optional[object]:
    """Newest retriable task's worker first; non-retriable only as a last
    resort (reference: RetriableLIFOWorkerKillingPolicy,
    worker_killing_policy.cc:32 — retriable before non-retriable, then
    task time descending)."""
    if not candidates:
        return None
    retriable = [c for c in candidates if _retriable(c[1])]
    pool = retriable or candidates
    return max(pool, key=lambda c: c[2])[0]


def group_by_owner_policy(candidates: List[Candidate]) -> Optional[object]:
    """Group by (owner, retriable); prefer retriable groups, then larger
    groups, then the group whose newest member is youngest; kill the newest
    worker in the chosen group (reference:
    worker_killing_policy_group_by_owner.h:85)."""
    if not candidates:
        return None
    groups: dict = {}
    for c in candidates:
        spec = c[1]
        owner = spec.owner_worker_id.binary() if spec.owner_worker_id else b""
        groups.setdefault((owner, _retriable(spec)), []).append(c)

    def rank(item):
        (_, retriable), members = item
        newest = max(m[2] for m in members)
        return (retriable, len(members), newest)

    _, members = max(groups.items(), key=rank)
    return max(members, key=lambda c: c[2])[0]


POLICIES = {
    "group_by_owner": group_by_owner_policy,
    "retriable_lifo": retriable_lifo_policy,
}


class MemoryMonitor:
    """Periodically evaluated by the head's health-monitor loop: when usage
    crosses the threshold, kill one local worker per check period (gradual
    pressure relief, like the reference's one-kill-per-interval pacing)."""

    def __init__(self, head):
        from ray_tpu._private.config import CONFIG

        self.head = head
        self.threshold = CONFIG.memory_usage_threshold
        self.period_s = CONFIG.memory_monitor_refresh_ms / 1000.0
        name = CONFIG.worker_killing_policy
        if name not in POLICIES:
            # Reference behavior (worker_killing_policy.cc:105): warn and
            # fall back to the default rather than crashing init.
            import warnings

            warnings.warn(
                f"worker_killing_policy={name!r} is invalid (choices: "
                f"{sorted(POLICIES)}); defaulting to retriable_lifo")
            name = "retriable_lifo"
        self.policy = POLICIES[name]
        self._test_file = CONFIG.memory_monitor_test_file
        self._last_check = 0.0
        self.kill_count = 0  # observability: surfaced via state API stats

    @property
    def enabled(self) -> bool:
        return self.period_s > 0

    def usage(self) -> float:
        if self._test_file:
            try:
                with open(self._test_file) as f:
                    return float(f.read().strip() or 0.0)
            except (OSError, ValueError):
                return 0.0
        return host_memory_usage_fraction()

    def tick(self) -> None:
        """Called under the head lock from the monitor loop."""
        now = time.monotonic()
        if not self.enabled or now - self._last_check < self.period_s:
            return
        self._last_check = now
        usage = self.usage()
        if usage < self.threshold:
            return
        victim = self.policy(self._candidates())
        if victim is None:
            # Last resort: actor workers.  The reference's policies rank
            # actors/non-retriable last rather than exempting them — a host
            # whose pressure comes from actors must still get relief (the
            # actor FSM's restart path rebuilds state afterwards).  Newest
            # actor first: it has accumulated the least state.
            victim = self._actor_last_resort()
        if victim is None:
            return
        self.kill_count += 1
        spec = victim.current_task
        self.head.gcs.publish(
            "oom",
            {"worker_id": victim.worker_id.hex(),
             "task": spec.name if spec else None,
             "usage": usage})
        # Mark so the death handler reports OutOfMemoryError (not a generic
        # crash, and with the usage at kill time) when the retry budget is
        # exhausted.
        if spec is not None:
            from ray_tpu._private.recovery import note

            note("oom_worker_kills")
            self.head._oom_killed[spec.task_id] = usage
        try:
            victim.proc.kill()
        except Exception:
            pass

    def _candidates(self) -> List[Candidate]:
        from ray_tpu._private.raylet import RemoteRaylet

        out: List[Candidate] = []
        for raylet in self.head.raylets.values():
            if isinstance(raylet, RemoteRaylet):
                # This monitor reads the HEAD host's memory; killing a
                # worker on another host frees nothing here (remote hosts
                # run their own pressure handling in the node agent).
                continue
            for h in raylet.workers.values():
                # Only busy workers running a normal (non-actor-bound)
                # task are eligible: killing an actor loses state the FSM
                # would have to rebuild, so actors are spared like the
                # reference's policy spares non-retriable groups until last.
                # proc.poll() is None filters corpses: a worker killed on
                # the previous tick may not be reaped yet (the liveness
                # scan runs after this tick), and re-selecting it would
                # waste the one-kill-per-period pacing on a dead process.
                if (h.current_task is not None and h.actor_id is None
                        and h.proc is not None and h.proc.poll() is None):
                    out.append((h, h.current_task, h.task_started_at))
        return out

    def _actor_last_resort(self):
        from ray_tpu._private.raylet import RemoteRaylet

        best, best_t = None, -1.0
        for raylet in self.head.raylets.values():
            if isinstance(raylet, RemoteRaylet):
                continue
            for h in raylet.workers.values():
                if (h.actor_id is not None and h.proc is not None
                        and h.proc.poll() is None):
                    # idle_since ~= registration time for actor workers
                    # (they never rejoin the idle pool): newest actor has
                    # accumulated the least state.
                    t = h.idle_since
                    if t > best_t:
                        best, best_t = h, t
        return best
