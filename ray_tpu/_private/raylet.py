"""Raylet: per-node manager — worker pool, local dispatch, node object store.

Equivalent of the reference's NodeManager + WorkerPool + LocalTaskManager
(src/ray/raylet/node_manager.h:115, worker_pool.h:156,
local_task_manager.h:58).  One Raylet instance per (possibly virtual) node;
all raylets of a local cluster live in the head process, workers are real
subprocesses.  Virtual multi-node is the test fixture the reference builds
with ray.cluster_utils.Cluster (python/ray/cluster_utils.py:99).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, Optional

from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.object_store import SharedMemoryStore
from ray_tpu._private.task_spec import TaskSpec, TaskType

DEFAULT_MAX_WORKERS = 64
IDLE_WORKER_TTL_S = 300.0


class WorkerHandle:
    __slots__ = ("worker_id", "proc", "conn", "busy", "actor_id", "node_id",
                 "current_task", "idle_since", "tpu_visible", "tpu_chips",
                 "task_started_at", "direct_addr", "leased_to", "lease_spec",
                 "blocked")

    def __init__(self, worker_id: WorkerID, proc, node_id: NodeID):
        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen (None until registered? no: set at spawn)
        self.conn = None  # set on register
        self.busy = False
        self.actor_id = None
        self.node_id = node_id
        self.current_task: Optional[TaskSpec] = None
        self.idle_since = time.monotonic()
        self.tpu_visible = False
        self.tpu_chips: tuple = ()  # chip indices this worker may touch
        self.task_started_at = 0.0  # dispatch time of current_task
        self.direct_addr = None  # the worker's own direct listener address
        self.leased_to = None    # caller worker id holding a lease on us
        self.lease_spec = None   # synthetic spec whose resources the lease holds
        self.blocked = False     # blocked in get(): resources released


class Raylet:
    """Node-local state. Thread-safety provided by the Head's single dispatch
    lock (all mutation happens under head._lock)."""

    def __init__(self, node_id: NodeID, head, store_capacity: int,
                 labels: Optional[dict] = None, max_workers: int = DEFAULT_MAX_WORKERS,
                 tpu_chips: int = 0):
        self.node_id = node_id
        self.head = head
        self.store = SharedMemoryStore(
            store_capacity,
            spill_dir=os.path.join(head.session_dir, "spill",
                                   node_id.hex()[:12]))
        self.labels = labels or {}
        self.max_workers = max_workers
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle: deque = deque()  # WorkerIDs of registered idle workers
        self.queued: deque = deque()  # TaskSpecs waiting for a free worker
        self.num_starting = 0
        self.consecutive_start_failures = 0
        self.dead = False
        # Chip partitioning: libtpu grabs every visible chip exclusively, so
        # two TPU-visible processes on one host MUST see disjoint chip sets
        # (TPU_VISIBLE_CHIPS) or the second hangs/fails at backend init.
        self.tpu_chips_total = int(tpu_chips)
        self._free_chips = list(range(self.tpu_chips_total))

    # ---- worker pool ----
    @staticmethod
    def _chips_needed(spec: TaskSpec) -> int:
        """Exclusive chips for a TPU spec: whole-number requests partition
        (ceil); fractional requests return 0 = *shared* mode — the worker
        is TPU-visible with no exclusive chip claim, because sharing is the
        declared intent and an exclusive grant would deadlock the peers the
        scheduler co-packed onto the same chip."""
        req = spec.resources.get("TPU", 0)
        if req < 1:
            return 0

        import math

        return int(math.ceil(req))

    @staticmethod
    def _needs_tpu(spec: TaskSpec) -> bool:
        return spec.resources.get("TPU", 0) > 0

    def ensure_worker(self, spec: Optional[TaskSpec] = None):
        """Spawn a new worker process if needed for `spec` (or any task)."""
        needs_tpu = spec is not None and self._needs_tpu(spec)
        needs_chips = self._chips_needed(spec) if needs_tpu else 0
        if needs_tpu:
            # TPU tasks need a TPU-visible worker whose chip share covers
            # the request.  A worker that is busy or permanently pinned to
            # an actor can never serve this spec, so "some TPU worker
            # exists" is not enough — that silently deadlocked a second TPU
            # actor on the same node.  Spawn another as long as none with
            # enough chips is *available or starting*; each TPU worker is
            # spawned onto a disjoint chip partition (TPU_VISIBLE_CHIPS) so
            # concurrent TPU workers never contend for the exclusive libtpu.
            for w in self.workers.values():
                if not w.tpu_visible:
                    continue
                # With an unknown topology (total == 0) every TPU worker
                # sees all chips, so chip-count matching is moot (same
                # guard as _pop_idle); shared-mode specs (needs_chips == 0)
                # are satisfied by any TPU-visible worker.
                if self.tpu_chips_total > 0 and len(w.tpu_chips) < needs_chips:
                    continue
                if w.conn is None:  # still starting — wait for it
                    return
                if not w.busy and w.actor_id is None:  # idle and claimable
                    return
            if len(self.workers) < self.max_workers:
                if needs_chips:
                    chips = self._allocate_chips(needs_chips)
                    if chips is None:
                        # No free chips: every chip is held by a live TPU
                        # worker.  The spec waits until one dies/releases
                        # (the scheduler already capped grants to the
                        # node's TPU total, so this only happens while a
                        # pinned worker is shutting down).
                        return
                else:
                    chips = ()  # shared mode: all chips visible, none owned
                self.spawn_worker(tpu_visible=True, tpu_chips=chips)
            return
        if self.idle or self.num_starting > 0:
            return
        if len(self.workers) + self.num_starting >= self.max_workers:
            return
        self.spawn_worker()

    def _allocate_chips(self, n: int) -> Optional[tuple]:
        """Reserve n chip indices for a new TPU worker (None if unavailable).
        With an unknown topology (tpu_chips_total == 0, e.g. fake-TPU CPU
        test nodes) partitioning is moot: return an empty share."""
        if self.tpu_chips_total == 0:
            return ()
        if len(self._free_chips) < n:
            return None
        chips = tuple(self._free_chips[:n])
        del self._free_chips[:n]
        return chips

    def _worker_env(self, worker_id: WorkerID, tpu_visible: bool,
                    tpu_chips: tuple) -> Dict[str, str]:
        """Env-var *overlay* every worker needs, local or remote (transport
        vars are added by the spawner — head socket locally, head TCP on
        agents; the spawner applies this on top of its inherited environ,
        then applies the non-TPU JAX_PLATFORMS=cpu setdefault)."""
        env = {
            "RAY_TPU_AUTHKEY": self.head.authkey.hex(),
            "RAY_TPU_NODE_ID": self.node_id.hex(),
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            # Host identity for the direct transport's endpoint selection
            # (same host => unix socket; cross-host => TCP).  RemoteRaylet
            # overrides with its agent's host key.
            "RAY_TPU_HOST_KEY": getattr(self, "host_key", None)
                                 or self.head.host_key,
        }
        # Tracing plane: ship the driver's RESOLVED tracing switch — the
        # flag may have been set via _system_config or enable_tracing(),
        # which a fresh subprocess's CONFIG would never see.
        try:
            from ray_tpu.util.tracing import tracing_enabled

            if tracing_enabled():
                env["RAY_TPU_TRACING_ENABLED"] = "1"
        except Exception:
            pass
        if tpu_visible and tpu_chips and len(tpu_chips) < self.tpu_chips_total:
            # Strict-subset chip share: partition via TPU_VISIBLE_CHIPS so
            # concurrent TPU workers on this host never contend for libtpu.
            # A worker granted ALL host chips keeps the default env — the
            # proven whole-host path (and the only case libtpu's default
            # topology handling needs).
            env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in tpu_chips)
            env["TPU_PROCESS_BOUNDS"] = "1,1,1"
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"{len(tpu_chips)},1,1"
        return env

    def spawn_worker(self, tpu_visible: bool = False,
                     tpu_chips: tuple = ()) -> WorkerID:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update(self._worker_env(worker_id, tpu_visible, tpu_chips))
        if not tpu_visible:
            # Workers are CPU-only so they never contend for the (exclusive)
            # TPU chips; mesh workers are spawned with tpu_visible=True.
            # Dropping the accelerator-plugin trigger vars also skips the
            # site hook's eager jax import, cutting worker cold-start by
            # seconds (the worker can still `import jax` lazily on CPU).
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        from ray_tpu._private import inject_pkg_pythonpath

        inject_pkg_pythonpath(env)
        env["RAY_TPU_HEAD_SOCKET"] = self.head.socket_path
        env["RAY_TPU_SESSION_DIR"] = self.head.session_dir
        # Per-worker log files, tailed by the head's LogMonitor and echoed
        # to the driver (reference: log_monitor.py:104).
        logs_dir = os.path.join(self.head.session_dir, "logs")
        os.makedirs(logs_dir, exist_ok=True)
        stem = os.path.join(logs_dir, f"worker-{worker_id.hex()[:16]}")
        out_f = open(stem + ".out", "ab")
        err_f = open(stem + ".err", "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.default_worker"],
                env=env,
                stdout=out_f,
                stderr=err_f,
            )
        finally:
            out_f.close()
            err_f.close()
        h = WorkerHandle(worker_id, proc, self.node_id)
        h.tpu_visible = tpu_visible
        h.tpu_chips = tuple(tpu_chips)
        self.workers[worker_id] = h
        self.num_starting += 1
        return worker_id

    def on_worker_registered(self, worker_id: WorkerID, conn,
                             direct_addr=None) -> Optional[WorkerHandle]:
        h = self.workers.get(worker_id)
        if h is None:
            return None
        h.conn = conn
        h.direct_addr = direct_addr
        self.num_starting = max(0, self.num_starting - 1)
        self.consecutive_start_failures = 0
        self.idle.append(worker_id)
        h.idle_since = time.monotonic()
        return h

    def on_worker_lost(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        h = self.workers.pop(worker_id, None)
        if h is None:
            return None
        try:
            self.idle.remove(worker_id)
        except ValueError:
            pass
        if h.tpu_chips:  # return the chip partition to the free pool
            self._free_chips.extend(h.tpu_chips)
            self._free_chips.sort()
            h.tpu_chips = ()
        return h

    # ---- dispatch ----
    def try_dispatch(self):
        """Hand queued task specs to idle workers; spawn workers as needed.
        Scans the whole queue so one spec waiting for a special worker
        (e.g. TPU-visible) doesn't block runnable work behind it.
        Called under the head lock whenever state changes."""
        progress = True
        while progress and self.queued:
            progress = False
            for spec in list(self.queued):
                worker = self._pop_idle(spec)
                if worker is None:
                    self.ensure_worker(spec)
                    continue
                self.queued.remove(spec)
                progress = True
                worker.busy = True
                worker.current_task = spec
                worker.task_started_at = time.monotonic()
                if spec.task_type == TaskType.ACTOR_CREATION:
                    worker.actor_id = spec.actor_id
                self.head.send_to_worker(worker, {"type": "execute", "spec": spec})

    def _pop_idle(self, spec: TaskSpec) -> Optional[WorkerHandle]:
        needs_tpu = self._needs_tpu(spec)
        needs_chips = self._chips_needed(spec) if needs_tpu else 0
        for _ in range(len(self.idle)):
            wid = self.idle.popleft()
            h = self.workers.get(wid)
            if h is None or h.conn is None:
                continue
            if needs_tpu and (
                    not h.tpu_visible
                    or (self.tpu_chips_total > 0
                        and len(h.tpu_chips) < needs_chips)):
                self.idle.append(wid)
                continue
            return h
        return None

    def queue_task(self, spec: TaskSpec):
        self.queued.append(spec)
        self.try_dispatch()

    def release_worker(self, worker: WorkerHandle):
        """Task finished: return worker to the idle pool (actors stay pinned)."""
        worker.busy = False
        worker.current_task = None
        if worker.actor_id is None:
            self.idle.append(worker.worker_id)
            worker.idle_since = time.monotonic()
        self.try_dispatch()

    def shutdown(self, keep_spilled: bool = False):
        self.dead = True
        for h in list(self.workers.values()):
            try:
                if h.conn is not None:
                    h.conn.send({"type": "shutdown"})
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for h in list(self.workers.values()):
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                try:
                    h.proc.kill()
                except Exception:
                    pass
        self.store.shutdown(keep_spilled=keep_spilled)


# ---------------------------------------------------------------------------
# Remote nodes (multi-host): head-side proxies for a node agent process
# ---------------------------------------------------------------------------
class RemoteStoreProxy:
    """Head-side handle for a store that lives in a node agent process.

    Mutations are forwarded over the agent connection; reads return None —
    the head never reads remote bytes, it hands out pull resolutions against
    the agent's ObjectTransferServer instead (the reference's raylet↔object
    manager split, src/ray/object_manager/object_manager.h:117)."""

    def __init__(self, raylet: "RemoteRaylet"):
        self._raylet = raylet
        self.arena = None
        self.evict_callback = None  # agents report via "object_evicted" msgs
        # Spill records reported by the agent ("object_spilled"): lets the
        # head hand same-host callers a direct spill-file resolution.
        self._spilled: Dict = {}

    def adopt(self, object_id, data_size: int, metadata: bytes,
              segment=None):
        self._raylet.send_agent({"type": "store_adopt",
                                 "oid": object_id.binary(),
                                 "size": data_size, "meta": metadata,
                                 "segment": segment})

    def segment_of(self, object_id):
        return None

    def delete(self, object_id, evicted: bool = False):
        self._spilled.pop(object_id, None)
        self._raylet.send_agent({"type": "store_delete",
                                 "oid": object_id.binary()})

    def note_spilled(self, object_id, path: str, meta: bytes, size: int):
        self._spilled[object_id] = (path, meta, size)

    def meta(self, object_id):
        return None

    def arena_lookup(self, object_id):
        return None

    def spilled_lookup(self, object_id):
        rec = self._spilled.get(object_id)
        if rec is None:
            return None
        path, meta, size = rec
        return {"kind": "spilled", "path": path, "meta": meta, "size": size}

    def get(self, object_id):
        return None

    def contains(self, object_id):
        return False

    def pin(self, object_id):
        pass

    def unpin(self, object_id):
        pass

    def stats(self):
        return {}

    def shutdown(self):
        pass


class _RemoteProc:
    """Popen stand-in for a worker subprocess living on another host.
    Liveness comes from the agent's worker_exit reports + the worker's own
    control connection, not from local polling."""

    def __init__(self, raylet: "RemoteRaylet", worker_id: WorkerID):
        self._raylet = raylet
        self._worker_id = worker_id
        self.returncode = None

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        self._raylet.send_agent({"type": "kill_worker",
                                 "worker_id": self._worker_id.binary()})


class RemoteRaylet(Raylet):
    """A raylet whose store + worker processes live on another host, driven
    through a NodeAgent connection (reference: the remote raylet the GCS
    talks to via NodeManagerService, src/ray/raylet/node_manager.h:115)."""

    def __init__(self, node_id: NodeID, head, agent_conn, host_key: str,
                 transfer_addr, labels: Optional[dict] = None,
                 max_workers: int = DEFAULT_MAX_WORKERS, tpu_chips: int = 0):
        # Deliberately NOT calling super().__init__: no local store.
        self.node_id = node_id
        self.head = head
        self.agent_conn = agent_conn
        self.host_key = host_key
        self.transfer_addr = tuple(transfer_addr)
        self.store = RemoteStoreProxy(self)
        self.labels = labels or {}
        self.max_workers = max_workers
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle: deque = deque()
        self.queued: deque = deque()
        self.num_starting = 0
        self.consecutive_start_failures = 0
        self.dead = False
        self.tpu_chips_total = int(tpu_chips)
        self._free_chips = list(range(self.tpu_chips_total))
        self._agent_lock = threading.Lock()

    def send_agent(self, msg: dict):
        try:
            with self._agent_lock:
                self.agent_conn.send(msg)
        except Exception:
            pass  # agent death is handled by its conn-close path

    def spawn_worker(self, tpu_visible: bool = False,
                     tpu_chips: tuple = ()) -> WorkerID:
        worker_id = WorkerID.from_random()
        env = self._worker_env(worker_id, tpu_visible, tpu_chips)
        if not tpu_visible:
            env["JAX_PLATFORMS"] = "cpu"
        self.send_agent({"type": "spawn_worker",
                         "worker_id": worker_id.binary(), "env": env})
        h = WorkerHandle(worker_id, _RemoteProc(self, worker_id), self.node_id)
        h.tpu_visible = tpu_visible
        h.tpu_chips = tuple(tpu_chips)
        self.workers[worker_id] = h
        self.num_starting += 1
        return worker_id

    def shutdown(self, keep_spilled: bool = False):
        self.dead = True
        self.send_agent({"type": "shutdown"})
        try:
            self.agent_conn.close()
        except Exception:
            pass
