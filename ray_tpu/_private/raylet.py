"""Raylet: per-node manager — worker pool, local dispatch, node object store.

Equivalent of the reference's NodeManager + WorkerPool + LocalTaskManager
(src/ray/raylet/node_manager.h:115, worker_pool.h:156,
local_task_manager.h:58).  One Raylet instance per (possibly virtual) node;
all raylets of a local cluster live in the head process, workers are real
subprocesses.  Virtual multi-node is the test fixture the reference builds
with ray.cluster_utils.Cluster (python/ray/cluster_utils.py:99).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, Optional

from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.object_store import SharedMemoryStore
from ray_tpu._private.task_spec import TaskSpec, TaskType

DEFAULT_MAX_WORKERS = 64
IDLE_WORKER_TTL_S = 300.0


class WorkerHandle:
    __slots__ = ("worker_id", "proc", "conn", "busy", "actor_id", "node_id",
                 "current_task", "idle_since", "tpu_visible")

    def __init__(self, worker_id: WorkerID, proc, node_id: NodeID):
        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen (None until registered? no: set at spawn)
        self.conn = None  # set on register
        self.busy = False
        self.actor_id = None
        self.node_id = node_id
        self.current_task: Optional[TaskSpec] = None
        self.idle_since = time.monotonic()
        self.tpu_visible = False


class Raylet:
    """Node-local state. Thread-safety provided by the Head's single dispatch
    lock (all mutation happens under head._lock)."""

    def __init__(self, node_id: NodeID, head, store_capacity: int,
                 labels: Optional[dict] = None, max_workers: int = DEFAULT_MAX_WORKERS):
        self.node_id = node_id
        self.head = head
        self.store = SharedMemoryStore(store_capacity)
        self.labels = labels or {}
        self.max_workers = max_workers
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle: deque = deque()  # WorkerIDs of registered idle workers
        self.queued: deque = deque()  # TaskSpecs waiting for a free worker
        self.num_starting = 0
        self.consecutive_start_failures = 0
        self.dead = False

    # ---- worker pool ----
    def ensure_worker(self, spec: Optional[TaskSpec] = None):
        """Spawn a new worker process if needed for `spec` (or any task)."""
        needs_tpu = spec is not None and spec.resources.get("TPU", 0) > 0
        if needs_tpu:
            # TPU tasks need a TPU-visible worker.  A worker that is busy or
            # permanently pinned to an actor can never serve this spec, so
            # "some TPU worker exists" is not enough — that silently
            # deadlocked a second TPU actor on the same node.  Spawn another
            # as long as none is *available or starting* and the node has
            # pool headroom (the scheduler already capped concurrent TPU
            # grants to the node's TPU resource total).
            for w in self.workers.values():
                if not w.tpu_visible:
                    continue
                if w.conn is None:  # still starting — wait for it
                    return
                if not w.busy and w.actor_id is None:  # idle and claimable
                    return
            if len(self.workers) < self.max_workers:
                self.spawn_worker(tpu_visible=True)
            return
        if self.idle or self.num_starting > 0:
            return
        if len(self.workers) + self.num_starting >= self.max_workers:
            return
        self.spawn_worker()

    def spawn_worker(self, tpu_visible: bool = False) -> WorkerID:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        # Ensure workers can import ray_tpu even when the driver added it to
        # sys.path manually rather than installing the package.
        import ray_tpu as _pkg

        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_HEAD_SOCKET"] = self.head.socket_path
        env["RAY_TPU_AUTHKEY"] = self.head.authkey.hex()
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_SESSION_DIR"] = self.head.session_dir
        if not tpu_visible:
            # Workers default to CPU so they never contend for the (exclusive)
            # TPU chips; mesh workers are spawned with tpu_visible=True.
            env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.default_worker"],
            env=env,
            stdout=None,
            stderr=None,
        )
        h = WorkerHandle(worker_id, proc, self.node_id)
        h.tpu_visible = tpu_visible
        self.workers[worker_id] = h
        self.num_starting += 1
        return worker_id

    def on_worker_registered(self, worker_id: WorkerID, conn) -> Optional[WorkerHandle]:
        h = self.workers.get(worker_id)
        if h is None:
            return None
        h.conn = conn
        self.num_starting = max(0, self.num_starting - 1)
        self.consecutive_start_failures = 0
        self.idle.append(worker_id)
        h.idle_since = time.monotonic()
        return h

    def on_worker_lost(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        h = self.workers.pop(worker_id, None)
        if h is None:
            return None
        try:
            self.idle.remove(worker_id)
        except ValueError:
            pass
        return h

    # ---- dispatch ----
    def try_dispatch(self):
        """Hand queued task specs to idle workers; spawn workers as needed.
        Scans the whole queue so one spec waiting for a special worker
        (e.g. TPU-visible) doesn't block runnable work behind it.
        Called under the head lock whenever state changes."""
        progress = True
        while progress and self.queued:
            progress = False
            for spec in list(self.queued):
                worker = self._pop_idle(spec)
                if worker is None:
                    self.ensure_worker(spec)
                    continue
                self.queued.remove(spec)
                progress = True
                worker.busy = True
                worker.current_task = spec
                if spec.task_type == TaskType.ACTOR_CREATION:
                    worker.actor_id = spec.actor_id
                self.head.send_to_worker(worker, {"type": "execute", "spec": spec})

    def _pop_idle(self, spec: TaskSpec) -> Optional[WorkerHandle]:
        needs_tpu = spec.resources.get("TPU", 0) > 0
        for _ in range(len(self.idle)):
            wid = self.idle.popleft()
            h = self.workers.get(wid)
            if h is None or h.conn is None:
                continue
            if needs_tpu and not h.tpu_visible:
                self.idle.append(wid)
                continue
            return h
        return None

    def queue_task(self, spec: TaskSpec):
        self.queued.append(spec)
        self.try_dispatch()

    def release_worker(self, worker: WorkerHandle):
        """Task finished: return worker to the idle pool (actors stay pinned)."""
        worker.busy = False
        worker.current_task = None
        if worker.actor_id is None:
            self.idle.append(worker.worker_id)
            worker.idle_since = time.monotonic()
        self.try_dispatch()

    def shutdown(self):
        self.dead = True
        for h in list(self.workers.values()):
            try:
                if h.conn is not None:
                    h.conn.send({"type": "shutdown"})
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for h in list(self.workers.values()):
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                try:
                    h.proc.kill()
                except Exception:
                    pass
        self.store.shutdown()
