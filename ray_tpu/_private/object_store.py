"""Plasma-equivalent shared-memory object store.

The reference implements this as a dlmalloc arena over one big mmap inside the
raylet (src/ray/object_manager/plasma/store.h:55, dlmalloc.cc) with fd-passing
to clients.  Our TPU-native design keeps the same *contract* — named,
immutable, sealed, zero-copy-readable shared-memory objects with create/seal/
get/delete and eviction accounting — but maps each object to its own POSIX
shm segment (``multiprocessing.shared_memory``), which any worker process on
the node can attach by name.  A C++ arena allocator (ray_tpu/_native) can be
slotted under the same interface later for allocation-rate-bound workloads;
for ML workloads the store holds few, large, numpy-backed objects
(SampleBatches, checkpoints, dataset blocks) where per-object segments are
ideal: the kernel does the zero-copy, and there is no fragmentation.

Small objects never come here — they live in the in-process memory store
(memory_store.py), exactly like the reference's CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43).
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Optional, Tuple

from ray_tpu._private.ids import ObjectID

# Objects <= this many bytes are inlined in task replies / the memory store.
INLINE_OBJECT_THRESHOLD = 100 * 1024

_PREFIX = "rtpu_"


def _segment_name(object_id: ObjectID) -> str:
    return _PREFIX + object_id.hex()


# Names this process has already told the resource tracker to forget; a
# second unregister makes the tracker process log KeyErrors at exit.
_untracked: set = set()


def untrack(shm: shared_memory.SharedMemory):
    """Tell the resource tracker this process does NOT own the segment.

    Python 3.12 registers every SharedMemory (even attaches) with the
    tracker, which would unlink live objects when this process exits."""
    name = shm._name  # type: ignore[attr-defined]
    if name in _untracked:
        return
    try:
        resource_tracker.unregister(name, "shared_memory")
        _untracked.add(name)
    except Exception:
        pass


def attach(object_id: ObjectID) -> shared_memory.SharedMemory:
    """Attach to an existing sealed object's segment (any process on node)."""
    shm = shared_memory.SharedMemory(name=_segment_name(object_id))
    untrack(shm)
    return shm


class PlasmaObject:
    __slots__ = ("shm", "metadata", "data_size", "sealed", "_view")

    def __init__(self, shm: shared_memory.SharedMemory, data_size: int):
        self.shm = shm
        self.metadata: bytes = b""
        self.data_size = data_size
        self.sealed = False
        # ONE canonical zero-copy view per object, handed to every writer
        # (create) and reader (get).  Readers slice it for chunked sends —
        # slices borrow the underlying mmap, not this view, so the store
        # can release it deterministically at delete time and shm.close()
        # stops failing with "cannot close exported pointers exist".
        self._view: Optional[memoryview] = None

    def view(self) -> memoryview:
        if self._view is None:
            self._view = (self.shm.buf[:self.data_size] if self.data_size
                          else memoryview(b""))
        return self._view

    def release_view(self) -> None:
        """Deterministic reclaim of the exported view (delete/shutdown
        path).  Any reader still holding the canonical view sees a
        released memoryview (ValueError on access) instead of silently
        leaking the whole segment mapping."""
        v, self._view = self._view, None
        if v is not None:
            try:
                v.release()
            except BufferError:
                pass  # a C-level buffer export is live; close() will leak
                # this one segment rather than crash the reader


class SharedMemoryStore:
    """Node-local store (owner side). Lives in the node's raylet.

    Accounting and LRU-style eviction of *unreferenced* sealed objects mirror
    plasma's ObjectLifecycleManager + EvictionPolicy
    (src/ray/object_manager/plasma/object_lifecycle_manager.h,
    eviction_policy.h).  Spill-to-disk hooks on eviction of referenced
    objects are the round-2 extension point (local_object_manager.h:41).
    """

    def __init__(self, capacity_bytes: int = 2 * 1024**3,
                 use_native_arena: bool = True,
                 spill_dir: Optional[str] = None):
        self.capacity = capacity_bytes
        self.used = 0
        self._objects: "OrderedDict[ObjectID, PlasmaObject]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._lock = threading.RLock()
        # Called with the ObjectID when LRU eviction frees an object, so the
        # object directory can mark it lost / trigger lineage reconstruction.
        self.evict_callback = None
        # Spilling (reference: local_object_manager.h:41): under memory
        # pressure, evicted objects whose bytes must survive (referenced /
        # unknown) are written to spill_dir instead of dropped; get()
        # restores them.  None disables spilling (pre-round-3 behavior).
        self.spill_dir = spill_dir
        self._spilled: Dict[ObjectID, Tuple[str, bytes, int]] = {}
        # Policy hook: should_spill(oid) -> bool.  When unset, every evicted
        # object spills (safe default for stores that cannot see refcounts,
        # e.g. on remote node agents); the head wires this to the object
        # directory so unreferenced objects are simply dropped.
        self.should_spill = None
        self.spill_callback = None  # notified with (oid) after a spill
        # Native C++ arena (plasma-core equivalent, ray_tpu/_native): used for
        # owner-process writes (driver puts).  Worker-created objects keep
        # the per-segment zero-round-trip path; both are zero-copy reads.
        self.arena = None
        from ray_tpu._private.config import CONFIG

        if use_native_arena and CONFIG.native_store:
            try:
                from ray_tpu import _native

                if _native.available():
                    self.arena = _native.NativeArenaStore(
                        "rtpu_arena_" + os.urandom(6).hex(), capacity_bytes)
            except Exception:
                self.arena = None

    # -- create/seal ------------------------------------------------------
    def create(self, object_id: ObjectID, data_size: int) -> memoryview:
        with self._lock:
            if object_id in self._objects:
                raise ObjectExistsError(object_id)
            if data_size > self.capacity:
                raise OutOfMemoryError(
                    f"object of {data_size} bytes exceeds store capacity {self.capacity}"
                )
            self._evict_until(data_size)
            if self.used + data_size > self.capacity:
                raise OutOfMemoryError(
                    f"store full: need {data_size}, "
                    f"free {self.capacity - self.used} of {self.capacity}"
                )
            shm = shared_memory.SharedMemory(
                name=_segment_name(object_id), create=True, size=max(1, data_size)
            )
            obj = PlasmaObject(shm, data_size)
            self._objects[object_id] = obj
            self.used += data_size
            return obj.view()

    def seal(self, object_id: ObjectID, metadata: bytes = b""):
        with self._lock:
            obj = self._objects[object_id]
            obj.metadata = metadata
            obj.sealed = True
            self._objects.move_to_end(object_id)

    def put(self, object_id: ObjectID, metadata: bytes, data: bytes) -> None:
        buf = self.create(object_id, len(data))
        if len(data):
            buf[:] = data
        self.seal(object_id, metadata)

    # -- read -------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            o = self._objects.get(object_id)
            return o is not None and o.sealed

    def get(self, object_id: ObjectID) -> Optional[Tuple[bytes, memoryview]]:
        """Returns (metadata, data) or None. Zero-copy: data is the
        object's canonical shm view — shared by all readers, reclaimed by
        the store at delete/shutdown (readers slice it for chunked sends;
        slices borrow the mmap directly and die with the reader)."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None or not obj.sealed:
                return None
            self._objects.move_to_end(object_id)  # LRU touch
            return obj.metadata, obj.view()

    def meta(self, object_id: ObjectID) -> Optional[bytes]:
        with self._lock:
            obj = self._objects.get(object_id)
            return obj.metadata if obj and obj.sealed else None

    # -- pin/delete/evict -------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def adopt(self, object_id: ObjectID, data_size: int, metadata: bytes):
        """Adopt a segment created (and already written) by a worker process.

        Workers create+write the segment directly — zero round-trips, like
        plasma's mmap'd create — then notify their raylet, which takes over
        ownership/accounting here."""
        with self._lock:
            if object_id in self._objects:
                return
            self._evict_until(data_size)
            if self.used + data_size > self.capacity:
                # The segment already exists (worker wrote it); adopting keeps
                # the data reachable but flags the overflow — the reference
                # instead backpressures at create time
                # (plasma create_request_queue.h); that needs a create RPC,
                # which trades away the zero-round-trip write path.
                import logging

                logging.getLogger(__name__).warning(
                    "object store over capacity: %d + %d > %d",
                    self.used, data_size, self.capacity)
            shm = attach(object_id)
            obj = PlasmaObject(shm, data_size)
            obj.metadata = metadata
            obj.sealed = True
            self._objects[object_id] = obj
            self.used += data_size

    def delete(self, object_id: ObjectID, evicted: bool = False,
               keep_spilled: bool = False):
        with self._lock:
            if self.arena is not None:
                self.arena.delete(object_id.binary())
            if not keep_spilled:
                self._drop_spill_file(object_id)
            obj = self._objects.pop(object_id, None)
            was_pinned = self._pinned.pop(object_id, None) is not None
            if obj is not None:
                self.used -= obj.data_size
                if not was_pinned:
                    # Reclaim the canonical exported view BEFORE close():
                    # without this every object ever read leaves an
                    # exported pointer and close() fails (the BufferError
                    # spam in the bench tail).  Pinned objects are being
                    # actively chunk-read; leave their view to the leak-
                    # tolerant path below rather than yank it mid-send.
                    obj.release_view()
                try:
                    obj.shm.unlink()
                except Exception:
                    pass
                try:
                    obj.shm.close()
                except BufferError:
                    pass  # a reader's transient chunk slice still borrows
                    # the mapping; it dies with the reader
                except Exception:
                    pass
                if evicted and self.evict_callback is not None:
                    try:
                        self.evict_callback(object_id)
                    except Exception:
                        pass

    def _evict_until(self, needed: int):
        # Evict unpinned sealed objects, least recently used first; objects
        # the policy says must survive are spilled to disk instead of
        # dropped (plasma eviction_policy.h + local_object_manager.h:41).
        if self.used + needed <= self.capacity:
            return
        for oid in list(self._objects.keys()):
            if self.used + needed <= self.capacity:
                break
            if oid in self._pinned:
                continue
            if not self._objects[oid].sealed:
                continue
            if self.spill_dir is not None and (
                    self.should_spill is None or self.should_spill(oid)):
                self._spill(oid)
            else:
                self.delete(oid, evicted=True)

    def _spill(self, oid: ObjectID):
        obj = self._objects.get(oid)
        if obj is None or not obj.sealed:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, oid.hex() + ".bin")
        with open(path, "wb") as f:
            f.write(obj.shm.buf[: obj.data_size])
        self._spilled[oid] = (path, obj.metadata, obj.data_size)
        # Free the memory; the spilled record + file survive this delete.
        self.delete(oid, keep_spilled=True)
        if self.spill_callback is not None:
            try:
                self.spill_callback(oid)
            except Exception:
                pass

    def spilled_lookup(self, oid: ObjectID):
        with self._lock:
            rec = self._spilled.get(oid)
            if rec is None:
                return None
            path, meta, size = rec
            return {"kind": "spilled", "path": path, "meta": meta,
                    "size": size}

    def read_spilled(self, oid: ObjectID) -> Optional[Tuple[bytes, bytes]]:
        with self._lock:
            rec = self._spilled.get(oid)
        if rec is None:
            return None
        path, meta, _ = rec
        try:
            with open(path, "rb") as f:
                return meta, f.read()
        except FileNotFoundError:
            return None

    def _drop_spill_file(self, oid: ObjectID):
        rec = self._spilled.pop(oid, None)
        if rec is not None:
            try:
                os.remove(rec[0])
            except OSError:
                pass

    # -- native arena paths (owner process only) --
    def arena_write(self, object_id: ObjectID, size: int) -> Optional[memoryview]:
        if self.arena is None:
            return None
        return self.arena.allocate(object_id.binary(), size)

    def arena_seal(self, object_id: ObjectID, metadata: bytes):
        self.arena.seal(object_id.binary(), metadata)

    def arena_lookup(self, object_id: ObjectID):
        if self.arena is None:
            return None
        hit = self.arena.lookup(object_id.binary())
        if hit is None:
            return None
        offset, size, meta = hit
        return {"kind": "arena", "store": self.arena.name, "offset": offset,
                "size": size, "meta": meta, "capacity": self.arena.capacity}

    def shutdown(self):
        with self._lock:
            for oid in list(self._objects.keys()):
                self.delete(oid)
            if self.arena is not None:
                self.arena.close()
                self.arena = None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "used_bytes": self.used,
                "capacity_bytes": self.capacity,
                "num_pinned": len(self._pinned),
            }


class ObjectExistsError(Exception):
    pass


class OutOfMemoryError(Exception):
    pass
