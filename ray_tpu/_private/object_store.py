"""Plasma-equivalent shared-memory object store.

The reference implements this as a dlmalloc arena over one big mmap inside the
raylet (src/ray/object_manager/plasma/store.h:55, dlmalloc.cc) with fd-passing
to clients.  Our TPU-native design keeps the same *contract* — named,
immutable, sealed, zero-copy-readable shared-memory objects with create/seal/
get/delete and eviction accounting — but maps each object to its own POSIX
shm segment (``multiprocessing.shared_memory``), which any worker process on
the node can attach by name.  A C++ arena allocator (ray_tpu/_native) can be
slotted under the same interface later for allocation-rate-bound workloads;
for ML workloads the store holds few, large, numpy-backed objects
(SampleBatches, checkpoints, dataset blocks) where per-object segments are
ideal: the kernel does the zero-copy, and there is no fragmentation.

Small objects never come here — they live in the in-process memory store
(memory_store.py), exactly like the reference's CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43).
"""
from __future__ import annotations

import atexit
import os
import threading
import weakref
from collections import OrderedDict, deque
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Optional, Tuple

from ray_tpu._private.ids import ObjectID

# Objects <= this many bytes are inlined in task replies / the memory store.
INLINE_OBJECT_THRESHOLD = 100 * 1024

_PREFIX = "rtpu_"

# Monotonic suffix for replica segment names (put_replica): replicas of the
# same object on different stores of one process must not collide.
_replica_counter = 0


def _segment_name(object_id: ObjectID) -> str:
    return _PREFIX + object_id.hex()


# Names this process has already told the resource tracker to forget; a
# second unregister makes the tracker process log KeyErrors at exit.
# Bounded: delete() calls forget_untracked() when a segment is unlinked,
# so long-lived drivers don't accumulate one entry per object ever seen.
_untracked: set = set()

# Segments THIS process created, keeps registered with the tracker, and
# will unlink itself (store-created + pooled segments).  A same-process
# attach must NOT untrack these: stripping the creator's registration
# makes the eventual unlink() a double-unregister (KeyError spam in the
# tracker daemon) and loses the crash-cleanup safety net.
_process_owned: set = set()


def note_owned(shm: shared_memory.SharedMemory):
    _process_owned.add(shm._name)  # type: ignore[attr-defined]


def untrack(shm: shared_memory.SharedMemory):
    """Tell the resource tracker this process does NOT own the segment.

    Python 3.12 registers every SharedMemory (even attaches) with the
    tracker, which would unlink live objects when this process exits."""
    name = shm._name  # type: ignore[attr-defined]
    if name in _untracked or name in _process_owned:
        return
    try:
        resource_tracker.unregister(name, "shared_memory")
        _untracked.add(name)
    except Exception:
        pass


def retrack(shm: shared_memory.SharedMemory):
    """Undo untrack() before this process unlinks the segment itself.

    unlink() unregisters the name with the tracker daemon; if untrack()
    already did, the daemon logs a KeyError per segment.  Used on the
    abort path of a worker's pull-into-store (the segment was created
    here, untracked in anticipation of the store adopting it, and must
    now be destroyed because the pull failed)."""
    name = shm._name  # type: ignore[attr-defined]
    if name in _untracked:
        try:
            resource_tracker.register(name, "shared_memory")
        except Exception:
            pass
        _untracked.discard(name)


def forget_untracked(shm: shared_memory.SharedMemory):
    """The segment is gone (unlinked): drop its bookkeeping entries so
    neither name set grows without bound in long-lived processes."""
    name = shm._name  # type: ignore[attr-defined]
    _untracked.discard(name)
    _process_owned.discard(name)


# Every SharedMemory this process opens (create or attach) is tracked in
# a weak set so interpreter shutdown can DEFUSE the mappings that still
# have live C-level buffer exports.  Zero-copy reads hand numpy views
# over segment mmaps to user code (sample batches, weights); when such a
# view survives to interpreter teardown, SharedMemory.__del__ -> close()
# -> mmap.close() raises "BufferError: cannot close exported pointers
# exist" and CPython prints an ignored-exception traceback per segment —
# the bench-tail spam.  The atexit hook below releases what is
# releasable and detaches the rest (fd closed, mmap handle dropped; the
# mapping itself dies with the process microseconds later).
_live_shms: "weakref.WeakSet[shared_memory.SharedMemory]" = weakref.WeakSet()


def track_for_exit(shm: shared_memory.SharedMemory
                   ) -> shared_memory.SharedMemory:
    _live_shms.add(shm)
    return shm


def defuse_shm(shm: shared_memory.SharedMemory) -> bool:
    """Deterministically release a segment handle that may still have
    exported buffer pointers.  Returns True when close() fully succeeded;
    on a live export the mmap/fd handles are dropped so a later __del__
    (or a second close()) is a silent no-op instead of a BufferError
    traceback."""
    try:
        shm.close()
        return True
    except BufferError:
        pass
    except Exception:
        return False
    buf = getattr(shm, "_buf", None)
    if buf is not None:
        try:
            buf.release()
        except BufferError:
            pass
        shm._buf = None  # type: ignore[attr-defined]
    # The mmap still has exporters (numpy views): leak the mapping — the
    # process is exiting (or the last view owner will drop it) — but
    # close the fd and clear the handles so __del__ cannot raise.
    shm._mmap = None  # type: ignore[attr-defined]
    fd = getattr(shm, "_fd", -1)
    if fd >= 0:
        try:
            os.close(fd)
        except OSError:
            pass
        shm._fd = -1  # type: ignore[attr-defined]
    return False


def _defuse_all_at_exit() -> None:
    for shm in list(_live_shms):
        try:
            defuse_shm(shm)
        except Exception:
            pass


# Registered at import (atexit is LIFO): runs AFTER the store/worker
# shutdown hooks registered later, as the last line of defense.
atexit.register(_defuse_all_at_exit)


# The atexit hook covers interpreter shutdown, but SharedMemory.__del__
# also fires whenever GC frees a segment handle while a consumer still
# holds numpy/arrow views into its mmap (zero-copy reads hand such views
# to user code); stock __del__ only swallows OSError, so the BufferError
# from mmap.close() escapes and CPython prints an ignored-exception
# traceback per segment — the bench-tail spam.  Route every __del__
# through the same defusal: try the normal close, and on a live export
# drop the handles instead of raising.  Locals are bound as defaults so
# the wrapper stays callable during late interpreter teardown.
_orig_shm_del = shared_memory.SharedMemory.__del__


def _shm_del(self, _orig=_orig_shm_del, _defuse=defuse_shm):
    try:
        _orig(self)
    except BufferError:
        try:
            _defuse(self)
        except Exception:
            pass
    except Exception:
        pass  # __del__ must never raise (late-shutdown torn-down globals)


shared_memory.SharedMemory.__del__ = _shm_del


def attach(object_id: ObjectID,
           segment: Optional[str] = None) -> shared_memory.SharedMemory:
    """Attach to an existing sealed object's segment (any process on node).

    ``segment`` overrides the canonical per-object name for objects whose
    bytes landed in a recycled pool segment (see SegmentPool)."""
    shm = shared_memory.SharedMemory(name=segment or _segment_name(object_id))
    untrack(shm)
    return track_for_exit(shm)


class SegmentPool:
    """Size-classed free lists of pre-created, pre-faulted shm segments.

    The reference gets its put throughput from a pre-mapped dlmalloc arena
    (plasma dlmalloc.cc): steady-state allocation never touches the kernel.
    Per-object segments pay ``shm_open + ftruncate + mmap`` per put and —
    far worse — fault in zero pages across the whole object on first
    write, capping large-put bandwidth at roughly half of memcpy.  The
    pool keeps that envelope with per-segment simplicity: segments are
    recycled through power-of-two size classes instead of unlinked, so a
    steady-state put reuses an already-mapped, already-faulted segment and
    runs at memcpy speed.

    Segments are named ``rtpu_pool_<pid>_<n>`` — readers learn the name
    from the object's resolution (``segment`` field) instead of deriving
    it from the object id.  Recycling follows plasma semantics: once an
    object's refcount hits zero its memory may be reused, so holding
    zero-copy views past the last ObjectRef is undefined (it was a
    stale-but-valid read in the unlink-per-object design).
    """

    MIN_CLASS = 1 << 20          # segments below 1 MiB aren't worth pooling
    MAX_CLASS = 1 << 31          # 2 GiB: larger objects get dedicated segments

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._free: Dict[int, deque] = {}
        self.free_bytes = 0
        self._lock = threading.Lock()
        self._counter = 0
        # Per-pool uniquifier: several stores (each with its own pool) can
        # live in ONE process — virtual multi-node clusters, a restarted
        # in-process head — and per-pid naming alone would collide.
        self._uid = os.urandom(3).hex()
        self._closed = False
        self._prewarm_thread: Optional[threading.Thread] = None
        self.hits = 0
        self.misses = 0
        self.created = 0

    @classmethod
    def class_for(cls, size: int) -> Optional[int]:
        if size > cls.MAX_CLASS:
            return None
        c = cls.MIN_CLASS
        while c < size:
            c <<= 1
        return c

    def _new_segment(self, cls_size: int) -> shared_memory.SharedMemory:
        with self._lock:
            self._counter += 1
            n = self._counter
        shm = shared_memory.SharedMemory(
            name=f"{_PREFIX}pool_{os.getpid()}_{self._uid}_{n}", create=True,
            size=cls_size)
        note_owned(shm)
        track_for_exit(shm)
        self.created += 1
        return shm

    def acquire(self, size: int
                ) -> Optional[Tuple[shared_memory.SharedMemory, int]]:
        """A segment of the right size class — recycled when one is free,
        freshly created otherwise.  None when the size is un-poolable."""
        cls_size = self.class_for(size)
        if cls_size is None or self._closed:
            return None
        with self._lock:
            q = self._free.get(cls_size)
            if q:
                self.hits += 1
                self.free_bytes -= cls_size
                return q.popleft(), cls_size
            self.misses += 1
        try:
            return self._new_segment(cls_size), cls_size
        except Exception:
            return None

    def release(self, shm: shared_memory.SharedMemory, cls_size: int) -> bool:
        """Return a segment to its free list.  False when the pool is full
        or closed — the caller unlinks the segment instead."""
        with self._lock:
            if self._closed or self.free_bytes + cls_size > self.max_bytes:
                return False
            self._free.setdefault(cls_size, deque()).append(shm)
            self.free_bytes += cls_size
            return True

    # -- background prewarm ------------------------------------------------
    def prewarm(self, spec: str):
        """Pre-create and pre-fault segments per a 'SIZE:COUNT,...' spec on
        a background thread, so the first puts of a fresh store hit the
        pool instead of faulting zero pages on the hot path."""
        plan = _parse_prewarm(spec)
        if not plan:
            return

        def run():
            for cls_size, count in plan:
                for _ in range(count):
                    if self._closed:
                        return
                    try:
                        shm = self._new_segment(cls_size)
                    except Exception:
                        return
                    _pretouch(shm.buf)
                    if not self.release(shm, cls_size):
                        _unlink_quiet(shm)
                        return

        self._prewarm_thread = threading.Thread(
            target=run, name="rtpu-pool-prewarm", daemon=True)
        self._prewarm_thread.start()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pool_hits": self.hits, "pool_misses": self.misses,
                    "pool_created": self.created,
                    "pool_free_bytes": self.free_bytes,
                    "pool_free_segments": sum(
                        len(q) for q in self._free.values())}

    def close(self):
        with self._lock:
            self._closed = True
            frees, self._free = list(self._free.values()), {}
            self.free_bytes = 0
        for q in frees:
            for shm in q:
                _unlink_quiet(shm)


def _parse_prewarm(spec: str):
    """'64MiB:4,8MiB:8' -> [(class_size, count), ...] (bad entries skipped)."""
    plan = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        size_s, _, count_s = part.partition(":")
        try:
            size = _parse_size(size_s)
            count = int(count_s)
        except ValueError:
            continue
        cls_size = SegmentPool.class_for(size)
        if cls_size is not None and count > 0:
            plan.append((cls_size, count))
    return plan


def _parse_size(s: str) -> int:
    s = s.strip().lower()
    for suffix, mult in (("kib", 1 << 10), ("mib", 1 << 20),
                         ("gib", 1 << 30), ("kb", 10**3), ("mb", 10**6),
                         ("gb", 10**9), ("k", 1 << 10), ("m", 1 << 20),
                         ("g", 1 << 30), ("b", 1)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(s)


def _pretouch(buf: memoryview, page: int = 4096):
    """Fault every page in (cheap sequential writes of one byte/page)."""
    try:
        import numpy as np

        arr = np.frombuffer(buf, dtype=np.uint8)
        arr[::page] = 0
    except Exception:
        for off in range(0, len(buf), page):
            buf[off] = 0


def _unlink_quiet(shm: shared_memory.SharedMemory):
    try:
        retrack(shm)  # unlink() re-unregisters; a no-op for owned names
        shm.unlink()
    except Exception:
        pass
    forget_untracked(shm)
    defuse_shm(shm)


class PlasmaObject:
    __slots__ = ("shm", "metadata", "data_size", "sealed", "_view",
                 "pool_class")

    def __init__(self, shm: shared_memory.SharedMemory, data_size: int,
                 pool_class: Optional[int] = None):
        self.shm = shm
        self.metadata: bytes = b""
        self.data_size = data_size
        self.sealed = False
        # Size class of the pooled segment backing this object (None for
        # dedicated per-object segments) — delete() recycles rather than
        # unlinks when set.
        self.pool_class = pool_class
        # ONE canonical zero-copy view per object, handed to every writer
        # (create) and reader (get).  Readers slice it for chunked sends —
        # slices borrow the underlying mmap, not this view, so the store
        # can release it deterministically at delete time and shm.close()
        # stops failing with "cannot close exported pointers exist".
        self._view: Optional[memoryview] = None

    def view(self) -> memoryview:
        if self._view is None:
            self._view = (self.shm.buf[:self.data_size] if self.data_size
                          else memoryview(b""))
        return self._view

    def release_view(self) -> bool:
        """Deterministic reclaim of the exported view (delete/shutdown
        path).  Any reader still holding the canonical view sees a
        released memoryview (ValueError on access) instead of silently
        leaking the whole segment mapping.  Returns False when a C-level
        buffer export is still live (the segment must NOT be recycled —
        the exporter would read freshly-written bytes)."""
        v, self._view = self._view, None
        if v is not None:
            try:
                v.release()
            except BufferError:
                return False  # a C-level buffer export is live; close()
                # will leak this one segment rather than crash the reader
        return True


class SharedMemoryStore:
    """Node-local store (owner side). Lives in the node's raylet.

    Accounting and LRU-style eviction of *unreferenced* sealed objects mirror
    plasma's ObjectLifecycleManager + EvictionPolicy
    (src/ray/object_manager/plasma/object_lifecycle_manager.h,
    eviction_policy.h).  Spill-to-disk hooks on eviction of referenced
    objects are the round-2 extension point (local_object_manager.h:41).
    """

    def __init__(self, capacity_bytes: int = 2 * 1024**3,
                 use_native_arena: bool = True,
                 spill_dir: Optional[str] = None):
        self.capacity = capacity_bytes
        self.used = 0
        self._objects: "OrderedDict[ObjectID, PlasmaObject]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._lock = threading.RLock()
        # Called with the ObjectID when LRU eviction frees an object, so the
        # object directory can mark it lost / trigger lineage reconstruction.
        self.evict_callback = None
        # Spilling (reference: local_object_manager.h:41): under memory
        # pressure, evicted objects whose bytes must survive (referenced /
        # unknown) are written to spill_dir instead of dropped; get()
        # restores them.  None disables spilling (pre-round-3 behavior).
        self.spill_dir = spill_dir
        self._spilled: Dict[ObjectID, Tuple[str, bytes, int]] = {}
        # Policy hook: should_spill(oid) -> bool.  When unset, every evicted
        # object spills (safe default for stores that cannot see refcounts,
        # e.g. on remote node agents); the head wires this to the object
        # directory so unreferenced objects are simply dropped.
        self.should_spill = None
        self.spill_callback = None  # notified with (oid) after a spill
        # Native C++ arena (plasma-core equivalent, ray_tpu/_native): used for
        # owner-process writes (driver puts).  Worker-created objects keep
        # the per-segment zero-round-trip path; both are zero-copy reads.
        self.arena = None
        from ray_tpu._private.config import CONFIG

        if use_native_arena and CONFIG.native_store:
            try:
                from ray_tpu import _native

                if _native.available():
                    self.arena = _native.NativeArenaStore(
                        "rtpu_arena_" + os.urandom(6).hex(), capacity_bytes)
            except Exception:
                self.arena = None
        # Segment pool: steady-state large puts reuse pre-faulted recycled
        # segments instead of paying shm_open + kernel page-zeroing per
        # object (see SegmentPool).  Free-list bytes are NOT charged to
        # `used` — like plasma's arena, pooled memory is store overhead.
        self.pool: Optional[SegmentPool] = None
        if CONFIG.segment_pool:
            pool_cap = CONFIG.segment_pool_bytes or capacity_bytes
            self.pool = SegmentPool(pool_cap)
            spec = CONFIG.segment_pool_prewarm
            if spec:
                self.pool.prewarm(spec)
        # Monotone create counter: "no new segments appeared here" checks
        # (e.g. the cooperative-broadcast smoke asserting the owner's
        # store stayed untouched) can't be fooled by a create+delete pair
        # the way num_objects can.
        self.segments_created_total = 0

    # -- create/seal ------------------------------------------------------
    def create(self, object_id: ObjectID, data_size: int,
               overcommit: bool = False,
               segment: Optional[str] = None) -> memoryview:
        """Allocate a writable segment for a new object.

        ``overcommit=True`` keeps the zero-round-trip in-process put path
        lossless under pressure: after eviction/spill the create proceeds
        even above capacity (the same contract adopt() gives worker-
        written segments) instead of raising.

        ``segment`` forces a dedicated shm segment with that name instead
        of the canonical per-object one — required for replica writes,
        where the canonical name may already exist on this machine (the
        primary copy in a sibling virtual node's store)."""
        with self._lock:
            if object_id in self._objects:
                raise ObjectExistsError(object_id)
            if data_size > self.capacity and not overcommit:
                raise OutOfMemoryError(
                    f"object of {data_size} bytes exceeds store capacity {self.capacity}"
                )
            self._evict_until(data_size)
            if self.used + data_size > self.capacity:
                if not overcommit:
                    raise OutOfMemoryError(
                        f"store full: need {data_size}, "
                        f"free {self.capacity - self.used} of {self.capacity}"
                    )
                import logging

                logging.getLogger(__name__).warning(
                    "object store over capacity: %d + %d > %d",
                    self.used, data_size, self.capacity)
            pool_class = None
            shm = None
            if segment is None and self.pool is not None \
                    and data_size >= SegmentPool.MIN_CLASS:
                acq = self.pool.acquire(data_size)
                if acq is not None:
                    shm, pool_class = acq
            if shm is None:
                shm = shared_memory.SharedMemory(
                    name=segment or _segment_name(object_id), create=True,
                    size=max(1, data_size))
                note_owned(shm)
                track_for_exit(shm)
            obj = PlasmaObject(shm, data_size, pool_class=pool_class)
            self._objects[object_id] = obj
            self.used += data_size
            self.segments_created_total += 1
            return obj.view()

    def segment_of(self, object_id: ObjectID) -> Optional[str]:
        """Segment name when it differs from the canonical per-object name
        (pooled segments, replica segments); None means readers derive it
        from the id."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                return None
            name = obj.shm.name
            return name if name != _segment_name(object_id) else None

    def seal(self, object_id: ObjectID, metadata: bytes = b""):
        with self._lock:
            obj = self._objects[object_id]
            obj.metadata = metadata
            obj.sealed = True
            self._objects.move_to_end(object_id)

    def put(self, object_id: ObjectID, metadata: bytes, data: bytes) -> None:
        buf = self.create(object_id, len(data))
        if len(data):
            buf[:] = data
        self.seal(object_id, metadata)

    def put_replica(self, object_id: ObjectID, metadata: bytes,
                    data) -> Optional[str]:
        """Store a durability replica of an object owned by another node.

        Always lands in a uniquely-named segment: on a multi-virtual-node
        machine the primary's canonical segment already exists host-wide,
        so a canonical-name create would collide.  Returns the segment
        name (readers resolve it via ``segment_of``), or None when the
        object is already present here."""
        global _replica_counter
        with self._lock:
            if object_id in self._objects:
                return self.segment_of(object_id)
            _replica_counter += 1
            seg = f"{_PREFIX}rep_{os.getpid()}_{_replica_counter}"
        try:
            buf = self.create(object_id, len(data), segment=seg)
        except ObjectExistsError:
            return self.segment_of(object_id)
        if len(data):
            buf[:] = data
        self.seal(object_id, metadata)
        return seg

    # -- read -------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            o = self._objects.get(object_id)
            return o is not None and o.sealed

    def get(self, object_id: ObjectID) -> Optional[Tuple[bytes, memoryview]]:
        """Returns (metadata, data) or None. Zero-copy: data is the
        object's canonical shm view — shared by all readers, reclaimed by
        the store at delete/shutdown (readers slice it for chunked sends;
        slices borrow the mmap directly and die with the reader)."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None or not obj.sealed:
                return None
            self._objects.move_to_end(object_id)  # LRU touch
            return obj.metadata, obj.view()

    def meta(self, object_id: ObjectID) -> Optional[bytes]:
        with self._lock:
            obj = self._objects.get(object_id)
            return obj.metadata if obj and obj.sealed else None

    # -- pin/delete/evict -------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def adopt(self, object_id: ObjectID, data_size: int, metadata: bytes,
              segment: Optional[str] = None):
        """Adopt a segment created (and already written) by a worker process.

        Workers create+write the segment directly — zero round-trips, like
        plasma's mmap'd create — then notify their raylet, which takes over
        ownership/accounting here."""
        with self._lock:
            if object_id in self._objects:
                return
            self._evict_until(data_size)
            shm = attach(object_id, segment)
            obj = PlasmaObject(shm, data_size)
            obj.metadata = metadata
            obj.sealed = True
            self._objects[object_id] = obj
            self.used += data_size
            if self.used > self.capacity:
                # The segment already exists (worker wrote it), so the
                # overflow is a fact; shed OTHER objects (evict or spill)
                # until the store is back under capacity instead of only
                # logging — the reference instead backpressures at create
                # time (plasma create_request_queue.h), which needs a
                # create RPC and trades away the zero-round-trip write.
                self._evict_until(0, exclude=object_id)
                if self.used > self.capacity:
                    import logging

                    logging.getLogger(__name__).warning(
                        "object store over capacity after adopt: %d > %d "
                        "(remaining objects pinned or unsealed)",
                        self.used, self.capacity)

    def delete(self, object_id: ObjectID, evicted: bool = False,
               keep_spilled: bool = False):
        with self._lock:
            if self.arena is not None:
                self.arena.delete(object_id.binary())
            if not keep_spilled:
                self._drop_spill_file(object_id)
            obj = self._objects.pop(object_id, None)
            was_pinned = self._pinned.pop(object_id, None) is not None
            if obj is not None:
                self.used -= obj.data_size
                view_clean = False
                if not was_pinned:
                    # Reclaim the canonical exported view BEFORE close():
                    # without this every object ever read leaves an
                    # exported pointer and close() fails (the BufferError
                    # spam in the bench tail).  Pinned objects are being
                    # actively chunk-read; leave their view to the leak-
                    # tolerant path below rather than yank it mid-send.
                    view_clean = obj.release_view()
                if (obj.pool_class is not None and view_clean
                        and self.pool is not None
                        and self.pool.release(obj.shm, obj.pool_class)):
                    # Recycled: the mapped, faulted segment goes back to
                    # its size-class free list for the next put.  Pinned
                    # or export-leaking segments are never recycled — an
                    # active reader must see stale bytes, not new ones.
                    pass
                else:
                    try:
                        # Adopted segments were attach-registered and then
                        # untracked; unlink()'s unregister must find the
                        # name registered or the tracker daemon logs a
                        # KeyError per deleted object.
                        retrack(obj.shm)
                        obj.shm.unlink()
                    except Exception:
                        pass
                    forget_untracked(obj.shm)
                    # defuse, not plain close: when a reader's view still
                    # borrows the mapping, a failed close() used to leave
                    # the handles set and __del__ retried it at interpreter
                    # shutdown — the BufferError traceback spam in the
                    # bench tail.  Defusing drops the handles so the
                    # mapping dies silently with its last view.
                    defuse_shm(obj.shm)
                if evicted and self.evict_callback is not None:
                    try:
                        self.evict_callback(object_id)
                    except Exception:
                        pass

    def _evict_until(self, needed: int, exclude: Optional[ObjectID] = None):
        # Evict unpinned sealed objects, least recently used first; objects
        # the policy says must survive are spilled to disk instead of
        # dropped (plasma eviction_policy.h + local_object_manager.h:41).
        if self.used + needed <= self.capacity:
            return
        for oid in list(self._objects.keys()):
            if self.used + needed <= self.capacity:
                break
            if oid == exclude or oid in self._pinned:
                continue
            if not self._objects[oid].sealed:
                continue
            if self.spill_dir is not None and (
                    self.should_spill is None or self.should_spill(oid)):
                self._spill(oid)
            else:
                self.delete(oid, evicted=True)

    def _spill(self, oid: ObjectID):
        obj = self._objects.get(oid)
        if obj is None or not obj.sealed:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, oid.hex() + ".bin")
        with open(path, "wb") as f:
            f.write(obj.shm.buf[: obj.data_size])
        self._spilled[oid] = (path, obj.metadata, obj.data_size)
        # Free the memory; the spilled record + file survive this delete.
        self.delete(oid, keep_spilled=True)
        if self.spill_callback is not None:
            try:
                self.spill_callback(oid)
            except Exception:
                pass

    def backup(self, oid: ObjectID) -> Optional[Tuple[str, bytes, int]]:
        """Durability spill: copy a sealed object's bytes to the spill dir
        WITHOUT evicting it — the in-memory copy keeps serving zero-copy
        reads, the disk copy survives this node's death (restore path:
        head-side spill records, see head._try_reconstruct).  Returns the
        (path, meta, size) record, or None when the object is gone or the
        store has no spill dir."""
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None or not obj.sealed or self.spill_dir is None:
                return self._spilled.get(oid)
            rec = self._spilled.get(oid)
            if rec is not None:
                return rec  # already on disk (spilled or backed up)
            os.makedirs(self.spill_dir, exist_ok=True)
            path = os.path.join(self.spill_dir, oid.hex() + ".bin")
            with open(path, "wb") as f:
                f.write(obj.shm.buf[: obj.data_size])
            rec = (path, obj.metadata, obj.data_size)
            self._spilled[oid] = rec
        if self.spill_callback is not None:
            try:
                self.spill_callback(oid)
            except Exception:
                pass
        return rec

    def spilled_lookup(self, oid: ObjectID):
        with self._lock:
            rec = self._spilled.get(oid)
            if rec is None:
                return None
            path, meta, size = rec
            return {"kind": "spilled", "path": path, "meta": meta,
                    "size": size}

    def read_spilled(self, oid: ObjectID) -> Optional[Tuple[bytes, bytes]]:
        with self._lock:
            rec = self._spilled.get(oid)
        if rec is None:
            return None
        path, meta, _ = rec
        try:
            with open(path, "rb") as f:
                return meta, f.read()
        except FileNotFoundError:
            return None

    def _drop_spill_file(self, oid: ObjectID):
        rec = self._spilled.pop(oid, None)
        if rec is not None:
            try:
                os.remove(rec[0])
            except OSError:
                pass

    # -- native arena paths (owner process only) --
    def arena_write(self, object_id: ObjectID, size: int) -> Optional[memoryview]:
        if self.arena is None:
            return None
        return self.arena.allocate(object_id.binary(), size)

    def arena_seal(self, object_id: ObjectID, metadata: bytes):
        self.arena.seal(object_id.binary(), metadata)

    def arena_lookup(self, object_id: ObjectID):
        if self.arena is None:
            return None
        hit = self.arena.lookup(object_id.binary())
        if hit is None:
            return None
        offset, size, meta = hit
        return {"kind": "arena", "store": self.arena.name, "offset": offset,
                "size": size, "meta": meta, "capacity": self.arena.capacity}

    def shutdown(self, keep_spilled: bool = False):
        """``keep_spilled=True`` is the node-death teardown: in-memory
        objects die with the store, but on-disk spill/backup copies are
        the durability plane's restore source and must survive."""
        with self._lock:
            for oid in list(self._objects.keys()):
                self.delete(oid, keep_spilled=keep_spilled)
            if self.arena is not None:
                self.arena.close()
                self.arena = None
            if self.pool is not None:
                self.pool.close()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {
                "num_objects": len(self._objects),
                "used_bytes": self.used,
                "capacity_bytes": self.capacity,
                "num_pinned": len(self._pinned),
                "segments_created_total": self.segments_created_total,
            }
            if self.pool is not None:
                out.update(self.pool.stats())
            return out


class ObjectExistsError(Exception):
    pass


class OutOfMemoryError(Exception):
    pass
