"""Serialization: cloudpickle + out-of-band zero-copy buffers for arrays.

Equivalent of the reference's SerializationContext
(python/ray/_private/serialization.py:92), redesigned for a JAX-first stack:

- cloudpickle (pickle protocol 5) for arbitrary Python objects,
- numpy arrays >= INLINE_THRESHOLD are carried as out-of-band
  ``PickleBuffer``s so the object store can place them contiguously and the
  reader can reconstruct a zero-copy view over shared memory,
- ``jax.Array``s are device_get'ed to numpy on write (host transfer is
  explicit and happens exactly once at the put-boundary; on-device data never
  travels through the object store — cross-mesh device data rides ICI/DCN via
  in-graph collectives, see ray_tpu/parallel/).
- ObjectRefs serialize by ID with an ownership record so the borrowing
  protocol can register them (see object_store.py / gcs.py).
"""
from __future__ import annotations

import io
import pickle
import threading
from typing import Any, List, Optional, Tuple

import cloudpickle
import numpy as np

# Arrays below this size are pickled in-band.
INLINE_ARRAY_THRESHOLD = 1024

# Types safe for the plain-pickle fast path (see serialize()).
_SCALAR_FAST_TYPES = (type(None), bool, int, float, str, bytes)


# ---------------------------------------------------------------------------
# Parallel memcpy (pack_into hot path)
# ---------------------------------------------------------------------------
# numpy's assignment into a uint8 view is a real memcpy that RELEASES the
# GIL, so a small worker pool copies disjoint chunks of one large buffer
# concurrently and scales with memory bandwidth instead of one core.  The
# pool is process-global, lazily built, and sized by CONFIG.copy_threads
# (0 = auto).  Buffers below CONFIG.parallel_copy_min_bytes — and every
# copy when the pool resolves to a single thread — take the plain
# single-call path.
_copy_pool = None
_copy_pool_lock = threading.Lock()
_copy_threads = 0


def _get_copy_pool():
    global _copy_pool, _copy_threads
    if _copy_threads:
        return _copy_pool
    with _copy_pool_lock:
        if _copy_threads:
            return _copy_pool
        import os

        from ray_tpu._private.config import CONFIG

        n = CONFIG.copy_threads
        if n <= 0:
            n = min(4, max(1, (os.cpu_count() or 2) // 2))
        if n > 1:
            from concurrent.futures import ThreadPoolExecutor

            try:
                _copy_pool = ThreadPoolExecutor(
                    max_workers=n - 1, thread_name_prefix="rtpu-memcpy")
            except Exception:
                _copy_pool, n = None, 1
        _copy_threads = n
        return _copy_pool


def _memcpy(dst: memoryview, src: memoryview) -> None:
    """Copy src -> dst (equal-length byte views), in parallel chunks when
    the buffer is large enough and the copy pool has workers."""
    n = src.nbytes
    dst_a = np.frombuffer(dst, np.uint8)
    src_a = np.frombuffer(src, np.uint8)
    from ray_tpu._private.config import CONFIG

    pool = _get_copy_pool() if n >= CONFIG.parallel_copy_min_bytes else None
    if pool is None:
        dst_a[:] = src_a
        return
    nthreads = _copy_threads
    # 64-byte-aligned chunk bounds keep every slice cache-line disjoint.
    step = -(-n // nthreads + 63) & ~63
    futs = [pool.submit(_copy_chunk, dst_a, src_a, off, min(off + step, n))
            for off in range(step, n, step)]
    dst_a[:min(step, n)] = src_a[:min(step, n)]  # chunk 0 on this thread
    for f in futs:
        f.result()


def _copy_chunk(dst_a, src_a, lo: int, hi: int) -> None:
    dst_a[lo:hi] = src_a[lo:hi]


class _RefSerializationContext(threading.local):
    """Collects ObjectRefs seen while (de)serializing a value, so the caller
    can register borrows / contained-ids (reference: contained object ids in
    src/ray/core_worker/reference_count.h)."""

    def __init__(self):
        self.refs: List[Any] = []
        self.owners: dict = {}  # oid binary -> owner address dict
        self.active = False

    def start(self):
        self.refs = []
        self.owners = {}
        self.active = True

    def stop(self) -> List[Any]:
        self.active = False
        refs, self.refs = self.refs, []
        self.owners = {}
        return refs

    def stop_with_owners(self):
        self.active = False
        refs, self.refs = self.refs, []
        owners, self.owners = self.owners, {}
        return refs, owners


ref_context = _RefSerializationContext()


def _is_jax_array(value) -> bool:
    # Avoid importing jax unless the process already did.  sys.modules is
    # read WITHOUT the import lock, so another thread may be mid-`import
    # jax` right now (e.g. a train-loop thread's first jax import while an
    # actor-pool thread serializes a result): the module object exists but
    # `jax.Array` isn't bound yet.  No jax array can exist in the process
    # before that first import completes, so "not there yet" simply means
    # False — raising here used to kill the actor thread mid-reply and
    # hang the driver forever on a future that never resolves.
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    arr_type = getattr(jax, "Array", None)
    if arr_type is None:
        return False
    try:
        return isinstance(value, arr_type)
    except TypeError:
        return False


class SerializedObject:
    """A serialized value: a pickle blob + out-of-band raw buffers.

    Layout written to the object store:
        [8B pickle-len][pickle blob][buffer 0][buffer 1]...
    with an index of (offset, length) pairs carried in the metadata, so
    readers can rebuild zero-copy memoryviews.
    """

    __slots__ = ("inband", "buffers", "contained_refs", "contained_owners")

    def __init__(self, inband: bytes, buffers: List[Any], contained_refs: List[Any],
                 contained_owners: Optional[dict] = None):
        self.inband = inband
        self.buffers = buffers  # list of objects supporting the buffer protocol
        self.contained_refs = contained_refs
        # oid binary -> owner address for contained refs whose bytes live in
        # a process's in-process store (ownership protocol, see direct.py).
        self.contained_owners = contained_owners or {}

    @property
    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(memoryview(b).cast("B")) for b in self.buffers)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        raw = buf.raw()
        if raw.nbytes >= INLINE_ARRAY_THRESHOLD:
            buffers.append(buf)
            return False  # out-of-band
        return True  # serialize in-band

    if _is_jax_array(value):
        import jax

        value = np.asarray(jax.device_get(value))

    ref_context.start()
    try:
        # Plain pickle (C fast path, ~5x cheaper per call than a
        # CloudPickler instance) ONLY for scalar types that can never
        # reference a __main__-defined class: a plain pickle of such a
        # class succeeds by REFERENCE in the driver but fails to load in a
        # worker (whose __main__ is default_worker) — cloudpickle instead
        # serializes it by value.  Containers stay on cloudpickle because
        # their elements may embed arbitrary user types.
        if type(value) in _SCALAR_FAST_TYPES:
            inband = pickle.dumps(value, protocol=5)
        else:
            inband = cloudpickle.dumps(value, protocol=5,
                                       buffer_callback=buffer_callback)
    finally:
        contained, owners = ref_context.stop_with_owners()
    return SerializedObject(inband, [b.raw() for b in buffers], contained,
                            owners)


def deserialize(inband: bytes, buffers: List[memoryview]) -> Tuple[Any, List[Any]]:
    """Returns (value, contained_object_refs)."""
    ref_context.start()
    try:
        value = pickle.loads(inband, buffers=buffers)
    finally:
        contained = ref_context.stop()
    return value, contained


def pack(serialized: SerializedObject) -> Tuple[bytes, bytes]:
    """Pack into (metadata, data) byte strings for the object store.

    metadata is a small pickle of the buffer index; data is the concatenation
    of the in-band pickle and all raw buffers, 64-byte aligned so numpy views
    over shared memory are cache-line aligned (reference aligns to 64 in
    plasma: src/ray/object_manager/plasma/ allocation alignment).
    """
    if not serialized.buffers:
        # No out-of-band buffers: the data IS the in-band pickle (readers
        # slice data[:inband_len]; padding only matters for buffer align).
        return _bufferless_meta(len(serialized.inband)), serialized.inband
    offsets = []
    pos = _align(len(serialized.inband))
    for b in serialized.buffers:
        n = memoryview(b).cast("B").nbytes
        offsets.append((pos, n))
        pos = _align(pos + n)
    meta = pickle.dumps({"inband_len": len(serialized.inband), "buffers": offsets})
    out = io.BytesIO()
    out.write(serialized.inband)
    _pad(out, _align(len(serialized.inband)) - len(serialized.inband))
    for b, (off, n) in zip(serialized.buffers, offsets):
        assert out.tell() == off
        out.write(memoryview(b).cast("B"))
        _pad(out, _align(off + n) - (off + n))
    return meta, out.getvalue()


def packed_size(serialized: SerializedObject) -> int:
    pos = _align(len(serialized.inband))
    for b in serialized.buffers:
        n = memoryview(b).cast("B").nbytes
        pos = _align(pos + n)
    return pos


def pack_into(serialized: SerializedObject, dest: memoryview) -> bytes:
    """Zero-intermediate-copy pack directly into a writable memoryview
    (a shared-memory segment). Returns metadata."""
    offsets = []
    pos = _align(len(serialized.inband))
    for b in serialized.buffers:
        n = memoryview(b).cast("B").nbytes
        offsets.append((pos, n))
        pos = _align(pos + n)
    if offsets:
        meta = pickle.dumps({"inband_len": len(serialized.inband),
                             "buffers": offsets})
    else:
        meta = _bufferless_meta(len(serialized.inband))
    dest[: len(serialized.inband)] = serialized.inband
    for b, (off, n) in zip(serialized.buffers, offsets):
        # numpy memcpy (CPython's memoryview slice assignment takes a
        # bytewise path ~4x slower), split across the copy-thread pool
        # for large buffers — see _memcpy.
        _memcpy(dest[off:off + n], memoryview(b).cast("B"))
    return meta


def unpack(meta: bytes, data: memoryview) -> Tuple[Any, List[Any]]:
    """Inverse of pack/pack_into over a (possibly shared-memory) buffer.

    numpy arrays come back as zero-copy views over ``data``."""
    index = pickle.loads(meta)
    inband = bytes(data[: index["inband_len"]])
    buffers = [data[off : off + n] for off, n in index["buffers"]]
    return deserialize(inband, buffers)


def num_oob_buffers(meta: bytes) -> int:
    """Number of out-of-band buffers recorded in an object's metadata —
    i.e. whether deserializing it yields zero-copy views over the store."""
    return len(pickle.loads(meta)["buffers"])


# Bufferless-object metadata depends only on inband length; small puts
# (ints, short strings) mint one per call otherwise — a measurable slice
# of the sub-100KB put path.  Bounded dict, hot lengths stabilize fast.
_bufferless_meta_cache: dict = {}


def _bufferless_meta(inband_len: int) -> bytes:
    meta = _bufferless_meta_cache.get(inband_len)
    if meta is None:
        meta = pickle.dumps({"inband_len": inband_len, "buffers": ()})
        if len(_bufferless_meta_cache) < 4096:
            _bufferless_meta_cache[inband_len] = meta
    return meta


def _align(n: int, a: int = 64) -> int:
    return (n + a - 1) & ~(a - 1)


def _pad(out: io.BytesIO, n: int):
    if n:
        out.write(b"\x00" * n)
