"""Cluster scheduler: resource vectors, scheduling policies, placement groups.

Re-implements the reference's two-level scheduling *decision* layer —
ClusterResourceScheduler over resource vectors with hybrid/spread/
node-affinity/PG-bundle policies (src/ray/raylet/scheduling/
cluster_resource_scheduler.h:44, scheduling/policy/*.h) and the placement
group manager's 2-phase bundle reservation (src/ray/gcs/gcs_server/
gcs_placement_group_manager.h:222) — as one in-head component.  Dispatch to
workers (the reference's LocalTaskManager) lives in raylet.py.

TPU-specific: "TPU" is a first-class resource alongside CPU/memory, and
nodes carry topology labels (slice id, host index within slice) so the mesh
bootstrap layer (ray_tpu/parallel/mesh_group.py) can gang-schedule one worker
per TPU host with STRICT_PACK-per-slice semantics.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu._private.task_spec import SchedulingStrategy, TaskSpec

_EPS = 1e-9


class NodeResources:
    __slots__ = ("node_id", "total", "available", "labels")

    def __init__(self, node_id: NodeID, total: Dict[str, float], labels=None):
        self.node_id = node_id
        self.total = dict(total)
        self.available = dict(total)
        self.labels = labels or {}

    def fits(self, demand: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + _EPS >= v for k, v in demand.items())

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + _EPS >= v for k, v in demand.items())

    def allocate(self, demand: Dict[str, float]):
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) - v

    def release(self, demand: Dict[str, float]):
        for k, v in demand.items():
            self.available[k] = min(self.total.get(k, 0.0),
                                    self.available.get(k, 0.0) + v)

    def utilization(self) -> float:
        worst = 0.0
        for k, tot in self.total.items():
            if tot > 0:
                worst = max(worst, 1.0 - self.available.get(k, 0.0) / tot)
        return worst


class Bundle:
    __slots__ = ("index", "resources", "node_id")

    def __init__(self, index: int, resources: Dict[str, float]):
        self.index = index
        self.resources = dict(resources)
        self.node_id: Optional[NodeID] = None


class PlacementGroupInfo:
    __slots__ = ("pg_id", "bundles", "strategy", "state", "name",
                 "bundle_available", "creator")

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.pg_id = pg_id
        self.bundles = [Bundle(i, b) for i, b in enumerate(bundles)]
        self.strategy = strategy  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
        self.state = "PENDING"  # PENDING | CREATED | REMOVED | INFEASIBLE
        self.name = name
        # Per-bundle remaining resources, for tasks scheduled into the PG.
        self.bundle_available: List[Dict[str, float]] = []
        self.creator = None


class ClusterScheduler:
    """Thread-safe resource ledger + policy engine."""

    def __init__(self):
        self._lock = threading.RLock()
        self.nodes: Dict[NodeID, NodeResources] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        # Round-robin cursor for SPREAD scheduling.
        self._spread_cursor = 0
        # Node shapes an attached autoscaler can launch (reference:
        # infeasible tasks stay pending when the autoscaler's node types
        # could satisfy them — resource_demand_scheduler feasibility).
        # Set by StandardAutoscaler; empty means no autoscaler.  Instance
        # state: two heads in one process must not share capacity.
        self.external_capacity: list = []
        # Arg-locality policy knobs (reference: the locality-aware lease
        # policy, locality_aware_lease_policy.h): resident arg bytes
        # outrank utilization once a host holds at least min_bytes.
        from ray_tpu._private.config import CONFIG

        self.locality_enabled: bool = CONFIG.locality_scheduling
        self.locality_min_bytes: int = CONFIG.locality_min_bytes

    # ----- membership -----
    def add_node(self, node_id: NodeID, resources: Dict[str, float], labels=None):
        with self._lock:
            self.nodes[node_id] = NodeResources(node_id, resources, labels)

    def remove_node(self, node_id: NodeID) -> List[PlacementGroupInfo]:
        """Drop a node; demote placement groups that had a bundle there
        back to PENDING, releasing the SURVIVING bundles' reservations so
        the re-reservation pass doesn't double-allocate them.  Returns
        the demoted groups (the head requeues them for re-reservation)."""
        demoted: List[PlacementGroupInfo] = []
        with self._lock:
            self.nodes.pop(node_id, None)
            for pg in self.placement_groups.values():
                if pg.state != "CREATED" or not any(
                        b.node_id == node_id for b in pg.bundles):
                    continue
                for b in pg.bundles:
                    if b.node_id is not None and b.node_id != node_id:
                        n = self.nodes.get(b.node_id)
                        if n is not None:
                            n.release(b.resources)
                    b.node_id = None
                pg.bundle_available = []
                pg.state = "PENDING"  # needs re-reservation
                demoted.append(pg)
        return demoted

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = defaultdict(float)
            for n in self.nodes.values():
                for k, v in n.available.items():
                    out[k] += v
            return dict(out)

    def total_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = defaultdict(float)
            for n in self.nodes.values():
                for k, v in n.total.items():
                    out[k] += v
            return dict(out)

    # ----- task placement -----
    def pick_node(self, spec: TaskSpec,
                  preferred: Optional[NodeID] = None,
                  locality: Optional[Dict[NodeID, float]] = None
                  ) -> Optional[NodeID]:
        """Returns a node id and reserves the task's resources on it, or None
        if nothing fits right now.  Raises Infeasible if no node could ever
        fit the demand.

        ``locality`` maps node -> bytes of the task's ObjectRef args
        already resident on that node's host; above ``locality_min_bytes``
        it outranks utilization in the default policy (NODE_AFFINITY and
        PLACEMENT_GROUP placements are explicit and stay untouched; a
        soft affinity that falls back to the default policy keeps the
        locality signal)."""
        st = spec.scheduling_strategy
        with self._lock:
            if st.kind == "PLACEMENT_GROUP":
                return self._pick_in_pg(spec)
            if st.kind == "NODE_AFFINITY":
                node = self.nodes.get(st.node_id)
                if node is None:
                    if st.soft:
                        return self._pick_default(spec, None, locality)
                    raise Infeasible(f"node {st.node_id} not in cluster")
                if node.fits(spec.resources):
                    node.allocate(spec.resources)
                    return node.node_id
                return self._pick_default(spec, None, locality) if st.soft \
                    else None
            if st.kind == "SPREAD":
                return self._pick_spread(spec)
            return self._pick_default(spec, preferred, locality)

    def _check_feasible(self, spec: TaskSpec):
        if any(n.feasible(spec.resources) for n in self.nodes.values()):
            return
        for cap in self.external_capacity:
            if all(cap.get(k, 0.0) >= v
                   for k, v in spec.resources.items()):
                return  # the autoscaler can launch a node for this
        raise Infeasible(
            f"no node can ever satisfy {spec.resources}; "
            f"cluster totals {dict(self.total_resources())}"
        )

    def _pick_default(self, spec: TaskSpec, preferred: Optional[NodeID],
                      locality: Optional[Dict[NodeID, float]] = None
                      ) -> Optional[NodeID]:
        """Hybrid policy: prefer the caller's node until it passes a
        utilization threshold, then pack by score (reference:
        scheduling/policy/hybrid_scheduling_policy.h).  Resident arg
        bytes dominate the score once a host holds locality_min_bytes
        of them — below the threshold pure utilization packing wins, so
        tiny args never unbalance the cluster."""
        self._check_feasible(spec)
        if preferred is not None:
            n = self.nodes.get(preferred)
            if n is not None and n.fits(spec.resources) and n.utilization() < 0.5:
                n.allocate(spec.resources)
                return n.node_id
        if not (self.locality_enabled and locality):
            locality = None
        best, best_score = None, None
        for n in self.nodes.values():
            if not n.fits(spec.resources):
                continue
            loc = locality.get(n.node_id, 0.0) if locality else 0.0
            if loc < self.locality_min_bytes:
                loc = 0.0
            # pack: most resident bytes, then highest utilization
            score = (loc, n.utilization(), n.node_id.binary())
            if best is None or score > best_score:
                best, best_score = n, score
        if best is not None:
            best.allocate(spec.resources)
            return best.node_id
        return None

    def _pick_spread(self, spec: TaskSpec) -> Optional[NodeID]:
        self._check_feasible(spec)
        nodes = sorted(self.nodes.values(), key=lambda n: n.node_id.binary())
        for i in range(len(nodes)):
            n = nodes[(self._spread_cursor + i) % len(nodes)]
            if n.fits(spec.resources):
                self._spread_cursor = (self._spread_cursor + i + 1) % len(nodes)
                n.allocate(spec.resources)
                return n.node_id
        return None

    def _pick_in_pg(self, spec: TaskSpec) -> Optional[NodeID]:
        st = spec.scheduling_strategy
        pg = self.placement_groups.get(st.placement_group_id)
        if pg is None or pg.state != "CREATED":
            raise Infeasible(f"placement group {st.placement_group_id} not ready")
        indices = (range(len(pg.bundles)) if st.bundle_index < 0
                   else [st.bundle_index])
        for i in indices:
            avail = pg.bundle_available[i]
            if all(avail.get(k, 0.0) + _EPS >= v for k, v in spec.resources.items()):
                for k, v in spec.resources.items():
                    avail[k] = avail.get(k, 0.0) - v
                return pg.bundles[i].node_id
        return None

    def reacquire(self, node_id: NodeID, spec: TaskSpec):
        """Re-take a blocked worker's resources on unblock (reference:
        TaskUnblocked re-acquisition — may oversubscribe; availability can
        go negative until something completes)."""
        with self._lock:
            st = spec.scheduling_strategy
            if st.kind == "PLACEMENT_GROUP":
                pg = self.placement_groups.get(st.placement_group_id)
                if pg is not None and pg.state == "CREATED":
                    for b in pg.bundles:
                        if b.node_id == node_id:
                            avail = pg.bundle_available[b.index]
                            for k, v in spec.resources.items():
                                avail[k] = avail.get(k, 0.0) - v
                            return
                return
            n = self.nodes.get(node_id)
            if n is not None:
                n.allocate(spec.resources)

    def return_resources(self, node_id: NodeID, spec: TaskSpec):
        with self._lock:
            st = spec.scheduling_strategy
            if st.kind == "PLACEMENT_GROUP":
                pg = self.placement_groups.get(st.placement_group_id)
                if pg is not None and pg.state == "CREATED":
                    for b in pg.bundles:
                        if b.node_id == node_id:
                            avail = pg.bundle_available[b.index]
                            ok = True
                            for k, v in spec.resources.items():
                                if avail.get(k, 0.0) + v > b.resources.get(k, 0.0) + _EPS:
                                    ok = False
                            if ok:
                                for k, v in spec.resources.items():
                                    avail[k] = avail.get(k, 0.0) + v
                                return
                return
            n = self.nodes.get(node_id)
            if n is not None:
                n.release(spec.resources)

    # ----- placement groups (2-phase: reserve all or roll back) -----
    def create_placement_group(self, pg: PlacementGroupInfo) -> bool:
        """Try to reserve every bundle atomically (reference 2-phase commit:
        gcs_placement_group_scheduler.h). Returns True if CREATED."""
        with self._lock:
            if not self._reserve_bundles(pg):
                return False
            pg.bundle_available = [dict(b.resources) for b in pg.bundles]
            pg.state = "CREATED"
            self.placement_groups[pg.pg_id] = pg
            return True

    def _reserve_bundles(self, pg: PlacementGroupInfo) -> bool:
        reserved: List[Tuple[NodeResources, Bundle]] = []

        def rollback():
            for n, b in reserved:
                n.release(b.resources)
                b.node_id = None

        strategy = pg.strategy
        nodes = sorted(self.nodes.values(),
                       key=lambda n: -n.utilization())  # pack onto busy nodes first
        if strategy in ("STRICT_PACK",):
            for n in self.nodes.values():
                if _fits_sum(n, [b.resources for b in pg.bundles]):
                    for b in pg.bundles:
                        n.allocate(b.resources)
                        b.node_id = n.node_id
                        reserved.append((n, b))
                    return True
            return False
        used_nodes: set = set()
        for b in pg.bundles:
            placed = False
            for n in nodes:
                if strategy == "STRICT_SPREAD" and n.node_id in used_nodes:
                    continue
                if strategy == "SPREAD" and n.node_id in used_nodes:
                    continue  # prefer new nodes; fall back below
                if n.fits(b.resources):
                    n.allocate(b.resources)
                    b.node_id = n.node_id
                    reserved.append((n, b))
                    used_nodes.add(n.node_id)
                    placed = True
                    break
            if not placed and strategy == "SPREAD":
                for n in nodes:  # soft spread: reuse nodes if needed
                    if n.fits(b.resources):
                        n.allocate(b.resources)
                        b.node_id = n.node_id
                        reserved.append((n, b))
                        placed = True
                        break
            if not placed:
                rollback()
                return False
        return True

    def remove_placement_group(self, pg_id: PlacementGroupID):
        with self._lock:
            pg = self.placement_groups.pop(pg_id, None)
            if pg is None or pg.state != "CREATED":
                return
            for b in pg.bundles:
                n = self.nodes.get(b.node_id)
                if n is not None:
                    n.release(b.resources)
            pg.state = "REMOVED"

    def pg_feasible(self, pg: PlacementGroupInfo) -> bool:
        with self._lock:
            if pg.strategy == "STRICT_SPREAD":
                return len(self.nodes) >= len(pg.bundles) and all(
                    any(n.feasible(b.resources) for n in self.nodes.values())
                    for b in pg.bundles
                )
            if pg.strategy == "STRICT_PACK":
                demand: Dict[str, float] = defaultdict(float)
                for b in pg.bundles:
                    for k, v in b.resources.items():
                        demand[k] += v
                return any(n.feasible(dict(demand)) for n in self.nodes.values())
            return all(
                any(n.feasible(b.resources) for n in self.nodes.values())
                for b in pg.bundles
            )


def _fits_sum(node: NodeResources, demands: List[Dict[str, float]]) -> bool:
    """Whether the summed demand of all bundles fits the node right now."""
    total: Dict[str, float] = defaultdict(float)
    for d in demands:
        for k, v in d.items():
            total[k] += v
    return all(node.available.get(k, 0.0) + _EPS >= v
               for k, v in total.items())


class Infeasible(Exception):
    pass
