"""Jittable multi-agent envs.

Reference surface: MultiAgentEnv (rllib/env/multi_agent_env.py) — dict
obs/rewards keyed by agent id, per-agent done.  The TPU redesign keeps
agents as a leading ARRAY axis instead of dict keys: ``reset -> obs
[M, obs_dim]``, ``step(actions [M]) -> (obs [M, d], rewards [M], done)``
— fixed agent count, fully vmappable, no dict traffic inside jit.

``CoordinationGame``: the canonical shared-policy testbed.  M agents each
pick an action; everyone is rewarded when ALL picked the SAME action.
Observations carry the one-hot previous joint action plus the agent's own
one-hot id, so a shared policy must use the id/history to coordinate —
independent random play earns ~2^-(M-1), coordinated play earns 1 per
step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class CoordinationGame:
    num_agents = 2
    num_actions = 2
    max_steps = 16

    @property
    def obs_dim(self) -> int:
        # one-hot previous joint action (A^M) + one-hot agent id (M)
        return self.num_actions ** self.num_agents + self.num_agents

    def _obs(self, prev_joint: jax.Array) -> jax.Array:
        """[M, obs_dim] from the previous joint-action index."""
        joint_oh = jax.nn.one_hot(
            prev_joint, self.num_actions ** self.num_agents)
        ids = jnp.eye(self.num_agents)
        return jnp.concatenate(
            [jnp.tile(joint_oh[None, :], (self.num_agents, 1)), ids],
            axis=-1)

    def reset(self, rng):
        state = {
            "prev_joint": jnp.zeros((), jnp.int32),
            "t": jnp.zeros((), jnp.int32),
        }
        return state, self._obs(state["prev_joint"])

    def step(self, state, actions, rng):
        """actions: [M] int32."""
        match = jnp.all(actions == actions[0])
        rewards = jnp.where(match, 1.0, 0.0) * jnp.ones(self.num_agents)
        joint = jnp.sum(
            actions * (self.num_actions
                       ** jnp.arange(self.num_agents))).astype(jnp.int32)
        t = state["t"] + 1
        done = t >= self.max_steps
        reset_state, reset_obs = self.reset(rng)
        new_state = {
            "prev_joint": jnp.where(done, reset_state["prev_joint"], joint),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs = jnp.where(done, reset_obs, self._obs(joint))
        return new_state, obs, rewards, done, {}


MA_REGISTRY = {
    "CoordinationGame-v0": CoordinationGame,
}


def make_ma_env(name: str):
    if name not in MA_REGISTRY:
        raise ValueError(
            f"unknown multi-agent env {name!r}; have {list(MA_REGISTRY)}")
    return MA_REGISTRY[name]()


def ma_vector_reset(env, rng, num_games: int):
    """[G] games → (states, obs [G, M, d])."""
    return jax.vmap(env.reset)(jax.random.split(rng, num_games))


def ma_vector_step(env, states, actions, rng):
    """actions [G, M] → (states, obs [G, M, d], rewards [G, M], done [G])."""
    num = actions.shape[0]
    return jax.vmap(env.step)(states, actions,
                              jax.random.split(rng, num))
