"""Python-API envs for CPU actor rollouts (gym-style contract, since gym is
not a dependency).  Mirrors the reference's env layer (rllib/env/*.py) in
miniature: single env + VectorEnv.  NumPy mirrors of the JAX dynamics so
actor-path and Anakin-path PPO train on identical MDPs."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class PyCartPole:
    """CartPole-v1 (numpy). API: reset(seed) -> obs; step(a) -> (obs, r,
    terminated, truncated, info)."""

    num_actions = 2
    obs_dim = 4

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.state = None
        self.t = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, 4)
        self.t = 0
        return self.state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = 10.0 if action == 1 else -10.0
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + 0.05 * theta_dot ** 2 * sintheta) / 1.1
        thetaacc = (9.8 * sintheta - costheta * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costheta ** 2 / 1.1))
        xacc = temp - 0.05 * thetaacc * costheta / 1.1
        x += 0.02 * x_dot
        x_dot += 0.02 * xacc
        theta += 0.02 * theta_dot
        theta_dot += 0.02 * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.t += 1
        terminated = bool(abs(x) > 2.4 or abs(theta) > 0.2095)
        truncated = self.t >= 500
        return self.state.copy(), 1.0, terminated, truncated, {}


PY_REGISTRY = {"CartPole-v1": PyCartPole}


class GymEnvAdapter:
    """Bridge to gymnasium (reference: rllib's gym env integration,
    rllib/env/wrappers/ + algorithm_config.environment(str)): wraps any
    gymnasium env with a Discrete action space and flattenable Box
    observations into the py-env contract the actor-path rollout stack
    speaks (reset(seed)->obs, step(a)->(obs, r, terminated, truncated,
    info))."""

    def __init__(self, name: str, seed: Optional[int] = None, **make_kwargs):
        import gymnasium

        self.env = gymnasium.make(name, **make_kwargs)
        self._check_spaces(name, self.env)
        self._next_seed = seed

    def _check_spaces(self, name: str, env) -> None:
        """Validate + record the env's spaces (split out so wrappers and
        tests can run the contract check on an arbitrary env object)."""
        from gymnasium import spaces

        space = env.observation_space
        if not isinstance(space, spaces.Box):
            # Discrete/MultiDiscrete obs have a shape too, but flattening
            # a state INDEX to one float is a near-meaningless encoding —
            # reject instead of silently training on it.
            raise ValueError(
                f"gym env {name!r}: only Box observation spaces are "
                f"bridgeable (one-hot/embed discrete states in a wrapper "
                f"first), got {space}")
        self.obs_dim = int(np.prod(space.shape))
        # Pixel envs keep their [H, W, C] shape (and uint8 dtype) so the
        # CNN trunk + PixelPreprocess stack see raw frames; flat envs
        # flatten to float32 as before.
        self.obs_shape = (tuple(space.shape) if len(space.shape) == 3
                          else None)
        act = env.action_space
        if isinstance(act, spaces.Discrete):
            self.num_actions = int(act.n)
            self.action_dim = None
        elif isinstance(act, spaces.Box):
            # Continuous control: the SAC/TD3-family actor path drives
            # gym Box actions (reference: the torch algos on MuJoCo/
            # classic-control continuous envs).
            self.num_actions = None
            self.action_dim = int(np.prod(act.shape))
            self.action_low = np.asarray(act.low, np.float32).reshape(-1)
            self.action_high = np.asarray(act.high, np.float32).reshape(-1)
        else:
            raise ValueError(
                f"gym env {name!r}: only Discrete or Box action spaces "
                f"are bridgeable, got {act}")

    def _flat(self, obs) -> np.ndarray:
        if self.obs_shape is not None:
            return np.asarray(obs)  # raw frame, dtype preserved
        return np.asarray(obs, np.float32).reshape(-1)

    def reset(self, seed: Optional[int] = None):
        if seed is None:
            seed = self._next_seed
        self._next_seed = None  # gymnasium reseeds only when asked
        obs, _info = self.env.reset(seed=seed)
        return self._flat(obs)

    def step(self, action):
        if self.num_actions is not None:
            action = int(action)
        else:
            action = np.asarray(action, np.float32).reshape(
                self.env.action_space.shape)
        obs, reward, terminated, truncated, info = self.env.step(action)
        return (self._flat(obs), float(reward), bool(terminated),
                bool(truncated), info)

    def close(self):
        self.env.close()


class PixelPreprocess:
    """The DeepMind Atari preprocessing stack over any pixel py-env
    (reference: rllib/env/wrappers/atari_wrappers.py — MaxAndSkipEnv,
    WarpFrame 84x84 grayscale, FrameStack 4; fire-reset is ALE-specific
    and applied only when the inner env exposes a FIRE action meaning).

    Wraps a py-env-contract object whose observations are raw [H, W, C]
    frames; emits uint8 [size, size, stack] observations — the exact
    input tensor the NatureCNN trunk (and the reference's atari-ppo
    config) consumes."""

    def __init__(self, env, size: int = 84, stack: int = 4, skip: int = 4,
                 grayscale: bool = True):
        if getattr(env, "obs_shape", None) is None:
            raise ValueError("PixelPreprocess needs a pixel env exposing "
                             "obs_shape=[H, W, C]")
        if not grayscale and env.obs_shape[-1] != 1:
            # Silently dropping color channels is worse than refusing:
            # the output shape would look valid while the agent trains on
            # the red channel only.
            raise ValueError("grayscale=False requires single-channel "
                             f"frames, got C={env.obs_shape[-1]}")
        self.env = env
        self.size, self.stack, self.skip = size, stack, skip
        self.grayscale = grayscale
        self.num_actions = env.num_actions
        self.action_dim = getattr(env, "action_dim", None)
        self.obs_shape = (size, size, stack)
        self.obs_dim = size * size * stack
        h, w = env.obs_shape[0], env.obs_shape[1]
        # Area-style nearest resize indices (no cv2 in this image).
        self._rows = (np.arange(size) * h // size).astype(np.int64)
        self._cols = (np.arange(size) * w // size).astype(np.int64)
        self._frames = None

    def _warp(self, frame: np.ndarray) -> np.ndarray:
        if self.grayscale and frame.ndim == 3 and frame.shape[-1] == 3:
            frame = (frame[..., 0] * 0.299 + frame[..., 1] * 0.587
                     + frame[..., 2] * 0.114)
        elif frame.ndim == 3:
            frame = frame[..., 0]
        return frame[self._rows[:, None], self._cols].astype(np.uint8)

    def _emit(self) -> np.ndarray:
        return np.stack(self._frames, axis=-1)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs = self.env.reset(seed)
        f = self._warp(np.asarray(obs))
        self._frames = [f] * self.stack
        return self._emit()

    def step(self, action):
        total, terminated, truncated, info = 0.0, False, False, {}
        prev_raw, raw = None, None
        for _ in range(self.skip):
            prev_raw = raw  # frame from the PREVIOUS inner step
            raw, r, terminated, truncated, info = self.env.step(action)
            total += r
            if terminated or truncated:
                break
        raw = np.asarray(raw)
        if prev_raw is not None:
            # Max-pool the last two raw frames (ALE flicker removal:
            # sprites drawn on alternate frames survive the skip).
            raw = np.maximum(raw, np.asarray(prev_raw))
        self._frames = self._frames[1:] + [self._warp(raw)]
        return self._emit(), float(total), terminated, truncated, info

    def close(self):
        if hasattr(self.env, "close"):
            self.env.close()


def wrap_pixel(name: str, size: int = 84, stack: int = 4, skip: int = 4,
               seed: Optional[int] = None, **make_kwargs):
    """Gym pixel env → DeepMind-preprocessed py env (the actor-path
    analogue of the on-device Atari84 envs)."""
    return PixelPreprocess(GymEnvAdapter(name, seed, **make_kwargs),
                           size=size, stack=stack, skip=skip)


def make_py_env(name: str, seed: Optional[int] = None):
    """Native registry first; anything else is resolved through the
    gymnasium bridge (so `.environment("Acrobot-v1")` in actor mode just
    works when gymnasium is installed)."""
    if callable(name):
        return name()
    if name in PY_REGISTRY:
        return PY_REGISTRY[name](seed)
    try:
        import gymnasium  # noqa: F401
    except ImportError:
        raise ValueError(
            f"unknown env {name!r} (native registry: {list(PY_REGISTRY)}; "
            f"install gymnasium for the gym bridge)") from None
    return GymEnvAdapter(name, seed)


def _step_one(env, action):
    """One env step with the vector contract: scalar actions cast to int,
    auto-reset on termination.  The ONE copy of the per-env semantics, so
    serial/thread/subprocess modes are step-equivalent by construction."""
    o, r, term, trunc, info = env.step(
        int(action) if np.ndim(action) == 0 else action)
    done = term or trunc
    if done:
        o = env.reset()
    return o, r, done, info


def _resolve_mode(mode: str, num_envs: int) -> str:
    if mode != "auto":
        return mode
    import os

    # Parallel stepping only pays when there are cores to step on and
    # enough envs to amortize the per-step fan-out.
    if (os.cpu_count() or 1) >= 4 and num_envs >= 4:
        return "subprocess"
    return "serial"


def _subproc_env_main(conn, env_fn_blob: bytes, indices, num_total: int,
                      seed: int):
    """Child process of a subprocess-mode VectorEnv: owns a slice of envs,
    steps them on command, and writes observations straight into the
    parent's shared-memory obs buffer (zero-copy hand-back; rewards/dones
    are tiny and ride the pipe reply)."""
    import cloudpickle
    import numpy as np

    env_fn = cloudpickle.loads(env_fn_blob)
    envs = [env_fn() for _ in indices]
    probe = None
    for e, gi in zip(envs, indices):
        o = e.reset(seed + gi)
        if probe is None:
            probe = np.asarray(o)
    conn.send(("meta", tuple(probe.shape), probe.dtype.str))
    shm, obs_view = None, None
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return  # parent died: exit quietly
            cmd = msg[0]
            if cmd == "attach":
                from multiprocessing import shared_memory

                shm = shared_memory.SharedMemory(name=msg[1])
                # NO untrack here: a spawn child shares the parent's
                # resource-tracker daemon, so unregistering would strip
                # the parent's registration and make its eventual
                # unlink() a tracker KeyError.  The attach-side register
                # dedups into the parent's entry.
                obs_view = np.ndarray((num_total,) + tuple(probe.shape),
                                      dtype=np.dtype(msg[2]), buffer=shm.buf)
                conn.send(("ok",))
            elif cmd == "reset":
                for e, gi in zip(envs, indices):
                    obs_view[gi] = e.reset()
                conn.send(("ok",))
            elif cmd == "step":
                actions = msg[1]
                rews, dones, infos = [], [], []
                for a, e, gi in zip(actions, envs, indices):
                    o, r, done, info = _step_one(e, a)
                    obs_view[gi] = o
                    rews.append(r)
                    dones.append(done)
                    infos.append(info)
                conn.send((np.asarray(rews, np.float32),
                           np.asarray(dones), infos))
            elif cmd == "close":
                conn.send(("ok",))
                return
    finally:
        for e in envs:
            if hasattr(e, "close"):
                try:
                    e.close()
                except Exception:
                    pass
        if obs_view is not None:
            del obs_view
        if shm is not None:
            try:
                shm.close()
            except Exception:
                pass


def _slice_indices(num_envs: int, num_workers: int) -> List[List[int]]:
    """Contiguous env-index slices, one per worker (serial order preserved
    inside each slice so trajectories match the serial mode exactly)."""
    base, rem = divmod(num_envs, num_workers)
    out, start = [], 0
    for w in range(num_workers):
        n = base + (1 if w < rem else 0)
        out.append(list(range(start, start + n)))
        start += n
    return [s for s in out if s]


class VectorEnv:
    """N python envs stepped together (reference: rllib/env/vector_env.py
    + the subprocess fan-out of vector_env.py's remote modes).

    ``mode``:

    - ``"serial"`` (default): step envs in a python loop in this process.
    - ``"thread"``: persistent worker threads each own a contiguous slice
      of envs and step them concurrently, writing into preallocated
      [N, ...] buffers.  Pays off when env.step releases the GIL
      (numpy/C-backed dynamics); GIL-bound envs see no speedup but
      identical trajectories.
    - ``"subprocess"``: one child process per slice — true parallelism for
      GIL-bound envs (Box2D, ALE).  Observations come back through a
      preallocated shared-memory buffer (a recycled SegmentPool segment,
      the PR 3 object-plane allocator), so the per-step IPC payload is
      one tiny action message + reward/done reply per worker.
    - ``"auto"``: subprocess when the host has >= 4 cores and >= 4 envs,
      else serial.

    All modes are step-equivalent: same seeds => identical trajectories
    (guarded by tests/test_rollout_plane.py).
    """

    def __init__(self, env_fn, num_envs: int, seed: int = 0,
                 mode: str = "serial", num_workers: Optional[int] = None):
        self.num_envs = num_envs
        self.mode = _resolve_mode(mode, num_envs)
        if self.mode not in ("serial", "thread", "subprocess"):
            raise ValueError(f"unknown VectorEnv mode {mode!r}")
        import os

        if num_workers is None:
            num_workers = min(num_envs,
                              max(2, (os.cpu_count() or 2) // 2))
        self.num_workers = max(1, min(int(num_workers), num_envs))
        self.envs: List[Any] = []
        if self.mode == "subprocess":
            self._setup_subprocess(env_fn, seed)
        else:
            self.envs = [env_fn() for _ in range(num_envs)]
            for i, e in enumerate(self.envs):
                e.reset(seed + i)
            if self.mode == "thread":
                self._setup_threads()

    # ---- serial ---------------------------------------------------------
    def _step_serial(self, actions):
        obs, rews, dones, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, done, info = _step_one(e, a)
            obs.append(o)
            rews.append(r)
            dones.append(done)
            infos.append(info)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(dones), infos)

    # ---- threads --------------------------------------------------------
    def _setup_threads(self):
        import threading

        self._slices = _slice_indices(self.num_envs, self.num_workers)
        self._cv = threading.Condition()
        self._epoch = 0
        self._cmd: Optional[str] = None
        self._actions = None
        self._pending = 0
        self._err: Optional[BaseException] = None
        self._obs_buf = None  # allocated on first step/reset (shape probe)
        self._rew_buf = np.zeros(self.num_envs, np.float32)
        self._done_buf = np.zeros(self.num_envs, bool)
        self._info_buf: List[dict] = [{} for _ in range(self.num_envs)]
        self._threads = [
            threading.Thread(target=self._thread_main, args=(sl,),
                             name=f"rtpu-env-{i}", daemon=True)
            for i, sl in enumerate(self._slices)
        ]
        for t in self._threads:
            t.start()

    def _ensure_obs_buf(self, probe: np.ndarray):
        if self._obs_buf is None:
            self._obs_buf = np.zeros((self.num_envs,) + probe.shape,
                                     probe.dtype)

    def _thread_main(self, indices: List[int]):
        local_epoch = 0
        while True:
            with self._cv:
                while self._epoch == local_epoch:
                    self._cv.wait()
                local_epoch = self._epoch
                cmd, actions = self._cmd, self._actions
            if cmd == "close":
                return
            try:
                if cmd == "reset":
                    for gi in indices:
                        self._obs_buf[gi] = self.envs[gi].reset()
                else:
                    for gi in indices:
                        o, r, done, info = _step_one(self.envs[gi],
                                                     actions[gi])
                        self._obs_buf[gi] = o
                        self._rew_buf[gi] = r
                        self._done_buf[gi] = done
                        self._info_buf[gi] = info
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                with self._cv:
                    self._err = e
                    self._pending -= 1
                    self._cv.notify_all()
                continue
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()

    def _run_threads(self, cmd: str, actions=None):
        with self._cv:
            self._cmd, self._actions = cmd, actions
            self._pending = len(self._threads)
            self._err = None
            self._epoch += 1
            self._cv.notify_all()
            while self._pending > 0:
                self._cv.wait()
            if self._err is not None:
                raise self._err

    def _step_thread(self, actions):
        if self._obs_buf is None:
            raise RuntimeError(
                "thread-mode VectorEnv: call reset_all() before step() "
                "(the first reset defines the obs buffer shape)")
        self._run_threads("step", np.asarray(actions))
        return (self._obs_buf.copy(), self._rew_buf.copy(),
                self._done_buf.copy(), list(self._info_buf))

    # ---- subprocesses ---------------------------------------------------
    def _setup_subprocess(self, env_fn, seed: int):
        import multiprocessing as mp

        import cloudpickle

        self._slices = _slice_indices(self.num_envs, self.num_workers)
        ctx = mp.get_context("spawn")
        blob = cloudpickle.dumps(env_fn)
        self._conns, self._procs = [], []
        for sl in self._slices:
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_subproc_env_main,
                            args=(child, blob, sl, self.num_envs, seed),
                            daemon=True)
            p.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(p)
        metas = [self._recv(c) for c in self._conns]
        shape, dtype = tuple(metas[0][1]), np.dtype(metas[0][2])
        self._obs_shape, self._obs_dtype = shape, dtype
        nbytes = int(np.prod((self.num_envs,) + shape)) * dtype.itemsize
        self._shm, self._shm_pool_class, self._pool = \
            self._alloc_obs_segment(max(1, nbytes))
        self._obs_np = np.ndarray((self.num_envs,) + shape, dtype,
                                  buffer=self._shm.buf)
        self._obs_np[:] = 0
        for c in self._conns:
            c.send(("attach", self._shm.name, dtype.str))
        for c in self._conns:
            self._recv(c)

    @staticmethod
    def _alloc_obs_segment(nbytes: int):
        """Obs buffer segment: a recycled SegmentPool segment (pre-faulted,
        power-of-two class) when poolable, else a dedicated segment."""
        from multiprocessing import shared_memory

        from ray_tpu._private.object_store import SegmentPool, note_owned

        pool = SegmentPool(max_bytes=2 * SegmentPool.MIN_CLASS + 2 * nbytes)
        acq = pool.acquire(nbytes)
        if acq is not None:
            shm, cls = acq
            return shm, cls, pool
        import os

        shm = shared_memory.SharedMemory(
            name=f"rtpu_venv_{os.getpid()}_{id(pool) & 0xffffff:x}",
            create=True, size=nbytes)
        note_owned(shm)
        return shm, None, pool

    def _recv(self, conn):
        try:
            return conn.recv()
        except (EOFError, OSError) as e:
            raise RuntimeError(
                "VectorEnv subprocess died (env worker crashed or was "
                "killed)") from e

    def _step_subprocess(self, actions):
        actions = np.asarray(actions)
        for c, sl in zip(self._conns, self._slices):
            c.send(("step", actions[sl[0]: sl[-1] + 1]))
        rews = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, bool)
        infos: List[dict] = [{}] * self.num_envs
        for c, sl in zip(self._conns, self._slices):
            r, d, inf = self._recv(c)
            rews[sl[0]: sl[-1] + 1] = r
            dones[sl[0]: sl[-1] + 1] = d
            infos[sl[0]: sl[-1] + 1] = inf
        return self._obs_np.copy(), rews, dones, infos

    # ---- public API ------------------------------------------------------
    def reset_all(self) -> np.ndarray:
        if self.mode == "subprocess":
            for c in self._conns:
                c.send(("reset",))
            for c in self._conns:
                self._recv(c)
            return self._obs_np.copy()
        if self.mode == "thread":
            if self._obs_buf is None:
                # First reset_all runs inline: the first obs defines the
                # buffer shape/dtype.  Each env resets exactly once (same
                # RNG draws as serial mode).
                first = np.asarray(self.envs[0].reset())
                self._ensure_obs_buf(first)
                self._obs_buf[0] = first
                for gi in range(1, self.num_envs):
                    self._obs_buf[gi] = self.envs[gi].reset()
                return self._obs_buf.copy()
            self._run_threads("reset")
            return self._obs_buf.copy()
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        if self.mode == "subprocess":
            return self._step_subprocess(actions)
        if self.mode == "thread":
            return self._step_thread(actions)
        return self._step_serial(actions)

    def close(self):
        if self.mode == "subprocess":
            for c in self._conns:
                try:
                    c.send(("close",))
                except (BrokenPipeError, OSError):
                    pass
            for p in self._procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
            for c in self._conns:
                try:
                    c.close()
                except Exception:
                    pass
            del self._obs_np
            from ray_tpu._private.object_store import _unlink_quiet

            _unlink_quiet(self._shm)
            self._pool.close()
            return
        if self.mode == "thread":
            with self._cv:
                self._cmd = "close"
                self._epoch += 1
                self._cv.notify_all()
            for t in self._threads:
                t.join(timeout=5.0)
        for e in self.envs:
            if hasattr(e, "close"):
                e.close()
