"""Python-API envs for CPU actor rollouts (gym-style contract, since gym is
not a dependency).  Mirrors the reference's env layer (rllib/env/*.py) in
miniature: single env + VectorEnv.  NumPy mirrors of the JAX dynamics so
actor-path and Anakin-path PPO train on identical MDPs."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class PyCartPole:
    """CartPole-v1 (numpy). API: reset(seed) -> obs; step(a) -> (obs, r,
    terminated, truncated, info)."""

    num_actions = 2
    obs_dim = 4

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.state = None
        self.t = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, 4)
        self.t = 0
        return self.state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = 10.0 if action == 1 else -10.0
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + 0.05 * theta_dot ** 2 * sintheta) / 1.1
        thetaacc = (9.8 * sintheta - costheta * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costheta ** 2 / 1.1))
        xacc = temp - 0.05 * thetaacc * costheta / 1.1
        x += 0.02 * x_dot
        x_dot += 0.02 * xacc
        theta += 0.02 * theta_dot
        theta_dot += 0.02 * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.t += 1
        terminated = bool(abs(x) > 2.4 or abs(theta) > 0.2095)
        truncated = self.t >= 500
        return self.state.copy(), 1.0, terminated, truncated, {}


PY_REGISTRY = {"CartPole-v1": PyCartPole}


class GymEnvAdapter:
    """Bridge to gymnasium (reference: rllib's gym env integration,
    rllib/env/wrappers/ + algorithm_config.environment(str)): wraps any
    gymnasium env with a Discrete action space and flattenable Box
    observations into the py-env contract the actor-path rollout stack
    speaks (reset(seed)->obs, step(a)->(obs, r, terminated, truncated,
    info))."""

    def __init__(self, name: str, seed: Optional[int] = None, **make_kwargs):
        import gymnasium

        self.env = gymnasium.make(name, **make_kwargs)
        self._check_spaces(name, self.env)
        self._next_seed = seed

    def _check_spaces(self, name: str, env) -> None:
        """Validate + record the env's spaces (split out so wrappers and
        tests can run the contract check on an arbitrary env object)."""
        from gymnasium import spaces

        space = env.observation_space
        if not isinstance(space, spaces.Box):
            # Discrete/MultiDiscrete obs have a shape too, but flattening
            # a state INDEX to one float is a near-meaningless encoding —
            # reject instead of silently training on it.
            raise ValueError(
                f"gym env {name!r}: only Box observation spaces are "
                f"bridgeable (one-hot/embed discrete states in a wrapper "
                f"first), got {space}")
        self.obs_dim = int(np.prod(space.shape))
        # Pixel envs keep their [H, W, C] shape (and uint8 dtype) so the
        # CNN trunk + PixelPreprocess stack see raw frames; flat envs
        # flatten to float32 as before.
        self.obs_shape = (tuple(space.shape) if len(space.shape) == 3
                          else None)
        act = env.action_space
        if isinstance(act, spaces.Discrete):
            self.num_actions = int(act.n)
            self.action_dim = None
        elif isinstance(act, spaces.Box):
            # Continuous control: the SAC/TD3-family actor path drives
            # gym Box actions (reference: the torch algos on MuJoCo/
            # classic-control continuous envs).
            self.num_actions = None
            self.action_dim = int(np.prod(act.shape))
            self.action_low = np.asarray(act.low, np.float32).reshape(-1)
            self.action_high = np.asarray(act.high, np.float32).reshape(-1)
        else:
            raise ValueError(
                f"gym env {name!r}: only Discrete or Box action spaces "
                f"are bridgeable, got {act}")

    def _flat(self, obs) -> np.ndarray:
        if self.obs_shape is not None:
            return np.asarray(obs)  # raw frame, dtype preserved
        return np.asarray(obs, np.float32).reshape(-1)

    def reset(self, seed: Optional[int] = None):
        if seed is None:
            seed = self._next_seed
        self._next_seed = None  # gymnasium reseeds only when asked
        obs, _info = self.env.reset(seed=seed)
        return self._flat(obs)

    def step(self, action):
        if self.num_actions is not None:
            action = int(action)
        else:
            action = np.asarray(action, np.float32).reshape(
                self.env.action_space.shape)
        obs, reward, terminated, truncated, info = self.env.step(action)
        return (self._flat(obs), float(reward), bool(terminated),
                bool(truncated), info)

    def close(self):
        self.env.close()


class PixelPreprocess:
    """The DeepMind Atari preprocessing stack over any pixel py-env
    (reference: rllib/env/wrappers/atari_wrappers.py — MaxAndSkipEnv,
    WarpFrame 84x84 grayscale, FrameStack 4; fire-reset is ALE-specific
    and applied only when the inner env exposes a FIRE action meaning).

    Wraps a py-env-contract object whose observations are raw [H, W, C]
    frames; emits uint8 [size, size, stack] observations — the exact
    input tensor the NatureCNN trunk (and the reference's atari-ppo
    config) consumes."""

    def __init__(self, env, size: int = 84, stack: int = 4, skip: int = 4,
                 grayscale: bool = True):
        if getattr(env, "obs_shape", None) is None:
            raise ValueError("PixelPreprocess needs a pixel env exposing "
                             "obs_shape=[H, W, C]")
        if not grayscale and env.obs_shape[-1] != 1:
            # Silently dropping color channels is worse than refusing:
            # the output shape would look valid while the agent trains on
            # the red channel only.
            raise ValueError("grayscale=False requires single-channel "
                             f"frames, got C={env.obs_shape[-1]}")
        self.env = env
        self.size, self.stack, self.skip = size, stack, skip
        self.grayscale = grayscale
        self.num_actions = env.num_actions
        self.action_dim = getattr(env, "action_dim", None)
        self.obs_shape = (size, size, stack)
        self.obs_dim = size * size * stack
        h, w = env.obs_shape[0], env.obs_shape[1]
        # Area-style nearest resize indices (no cv2 in this image).
        self._rows = (np.arange(size) * h // size).astype(np.int64)
        self._cols = (np.arange(size) * w // size).astype(np.int64)
        self._frames = None

    def _warp(self, frame: np.ndarray) -> np.ndarray:
        if self.grayscale and frame.ndim == 3 and frame.shape[-1] == 3:
            frame = (frame[..., 0] * 0.299 + frame[..., 1] * 0.587
                     + frame[..., 2] * 0.114)
        elif frame.ndim == 3:
            frame = frame[..., 0]
        return frame[self._rows[:, None], self._cols].astype(np.uint8)

    def _emit(self) -> np.ndarray:
        return np.stack(self._frames, axis=-1)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs = self.env.reset(seed)
        f = self._warp(np.asarray(obs))
        self._frames = [f] * self.stack
        return self._emit()

    def step(self, action):
        total, terminated, truncated, info = 0.0, False, False, {}
        prev_raw, raw = None, None
        for _ in range(self.skip):
            prev_raw = raw  # frame from the PREVIOUS inner step
            raw, r, terminated, truncated, info = self.env.step(action)
            total += r
            if terminated or truncated:
                break
        raw = np.asarray(raw)
        if prev_raw is not None:
            # Max-pool the last two raw frames (ALE flicker removal:
            # sprites drawn on alternate frames survive the skip).
            raw = np.maximum(raw, np.asarray(prev_raw))
        self._frames = self._frames[1:] + [self._warp(raw)]
        return self._emit(), float(total), terminated, truncated, info

    def close(self):
        if hasattr(self.env, "close"):
            self.env.close()


def wrap_pixel(name: str, size: int = 84, stack: int = 4, skip: int = 4,
               seed: Optional[int] = None, **make_kwargs):
    """Gym pixel env → DeepMind-preprocessed py env (the actor-path
    analogue of the on-device Atari84 envs)."""
    return PixelPreprocess(GymEnvAdapter(name, seed, **make_kwargs),
                           size=size, stack=stack, skip=skip)


def make_py_env(name: str, seed: Optional[int] = None):
    """Native registry first; anything else is resolved through the
    gymnasium bridge (so `.environment("Acrobot-v1")` in actor mode just
    works when gymnasium is installed)."""
    if callable(name):
        return name()
    if name in PY_REGISTRY:
        return PY_REGISTRY[name](seed)
    try:
        import gymnasium  # noqa: F401
    except ImportError:
        raise ValueError(
            f"unknown env {name!r} (native registry: {list(PY_REGISTRY)}; "
            f"install gymnasium for the gym bridge)") from None
    return GymEnvAdapter(name, seed)


class VectorEnv:
    """N python envs stepped together (reference: rllib/env/vector_env.py)."""

    def __init__(self, env_fn, num_envs: int, seed: int = 0):
        self.envs = [env_fn() for _ in range(num_envs)]
        self.num_envs = num_envs
        for i, e in enumerate(self.envs):
            e.reset(seed + i)

    def reset_all(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        obs, rews, dones, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(
                int(a) if np.ndim(a) == 0 else a)
            done = term or trunc
            if done:
                o = e.reset()
            obs.append(o)
            rews.append(r)
            dones.append(done)
            infos.append(info)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(dones), infos)

    def close(self):
        for e in self.envs:
            if hasattr(e, "close"):
                e.close()
