"""Python-API envs for CPU actor rollouts (gym-style contract, since gym is
not a dependency).  Mirrors the reference's env layer (rllib/env/*.py) in
miniature: single env + VectorEnv.  NumPy mirrors of the JAX dynamics so
actor-path and Anakin-path PPO train on identical MDPs."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class PyCartPole:
    """CartPole-v1 (numpy). API: reset(seed) -> obs; step(a) -> (obs, r,
    terminated, truncated, info)."""

    num_actions = 2
    obs_dim = 4

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.state = None
        self.t = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, 4)
        self.t = 0
        return self.state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = 10.0 if action == 1 else -10.0
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + 0.05 * theta_dot ** 2 * sintheta) / 1.1
        thetaacc = (9.8 * sintheta - costheta * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costheta ** 2 / 1.1))
        xacc = temp - 0.05 * thetaacc * costheta / 1.1
        x += 0.02 * x_dot
        x_dot += 0.02 * xacc
        theta += 0.02 * theta_dot
        theta_dot += 0.02 * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.t += 1
        terminated = bool(abs(x) > 2.4 or abs(theta) > 0.2095)
        truncated = self.t >= 500
        return self.state.copy(), 1.0, terminated, truncated, {}


PY_REGISTRY = {"CartPole-v1": PyCartPole}


def make_py_env(name: str, seed: Optional[int] = None):
    if callable(name):
        return name()
    if name not in PY_REGISTRY:
        raise ValueError(f"unknown env {name!r}")
    return PY_REGISTRY[name](seed)


class VectorEnv:
    """N python envs stepped together (reference: rllib/env/vector_env.py)."""

    def __init__(self, env_fn, num_envs: int, seed: int = 0):
        self.envs = [env_fn() for _ in range(num_envs)]
        self.num_envs = num_envs
        for i, e in enumerate(self.envs):
            e.reset(seed + i)

    def reset_all(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        obs, rews, dones, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(int(a))
            done = term or trunc
            if done:
                o = e.reset()
            obs.append(o)
            rews.append(r)
            dones.append(done)
            infos.append(info)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(dones), infos)
