"""JAX-native vectorized environments.

No equivalent exists in the reference: RLlib steps Python gym envs on CPU
rollout workers (rllib/evaluation/sampler.py).  The TPU-native design
additionally runs envs *inside the compiled program* (Podracer/Anakin
architecture, PAPERS.md) — thousands of env instances as a batched state
pytree, stepped by lax.scan on device, so rollout+learn is one jit with no
host↔device traffic.  CPU-actor rollouts (py_envs.py) remain for envs that
can't be expressed in JAX.

Env contract (functional, vmap/scan-safe):
    reset(rng) -> (state, obs)
    step(state, action, rng) -> (state, obs, reward, done, info)
Auto-reset on done is built into step (standard Anakin practice).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


class CartPole:
    """CartPole-v1 dynamics (matches the classic gym spec: 500-step limit,
    ±2.4 position, ±12° angle)."""

    num_actions = 2
    obs_dim = 4

    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 2 * jnp.pi / 360
    x_threshold = 2.4
    max_steps = 500

    def reset(self, rng) -> Tuple[Any, jax.Array]:
        core = jax.random.uniform(rng, (4,), minval=-0.05, maxval=0.05)
        state = {"core": core, "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(core)

    def _obs(self, core):
        """Observation from the 4-dim physical core; the stateless variant
        masks the velocity components here."""
        return core

    def step(self, state, action, rng):
        x, x_dot, theta, theta_dot = state["core"]
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        core = jnp.stack([x, x_dot, theta, theta_dot])
        t = state["t"] + 1
        done = (
            (jnp.abs(x) > self.x_threshold)
            | (jnp.abs(theta) > self.theta_threshold)
            | (t >= self.max_steps)
        )
        reward = jnp.ones(())
        # Auto-reset.
        reset_state, reset_obs = self.reset(rng)
        new_state = {
            "core": jnp.where(done, reset_state["core"], core),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs = jnp.where(done, reset_obs, self._obs(core))
        return new_state, obs, reward, done, {}


class Pendulum:
    """Pendulum-v1 with 3-bin discretized torque (keeps one categorical
    policy head across envs; continuous head lands with the SAC family)."""

    num_actions = 3
    obs_dim = 3
    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    l = 1.0
    max_steps = 200

    def reset(self, rng):
        k1, k2 = jax.random.split(rng)
        th = jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi)
        thdot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        state = {"th": th, "thdot": thdot, "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(state)

    def _obs(self, state):
        return jnp.stack([jnp.cos(state["th"]), jnp.sin(state["th"]),
                          state["thdot"]])

    def _torque(self, action):
        """Map the policy action to torque; the continuous subclass
        overrides this single hook so the dynamics stay in one place."""
        return (action.astype(jnp.float32) - 1.0) * self.max_torque

    def step(self, state, action, rng):
        u = self._torque(action)
        th, thdot = state["th"], state["thdot"]
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.l) * jnp.sin(th)
                         + 3.0 / (self.m * self.l ** 2) * u) * self.dt
        thdot = jnp.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        t = state["t"] + 1
        done = t >= self.max_steps
        reset_state, reset_obs = self.reset(rng)
        new_state = {
            "th": jnp.where(done, reset_state["th"], th),
            "thdot": jnp.where(done, reset_state["thdot"], thdot),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs_next = self._obs({"th": th, "thdot": thdot})
        obs = jnp.where(done, reset_obs, obs_next)
        return new_state, obs, -cost, done, {}


class Breakout:
    """Atari-class pixel Breakout on a 10x10 board (MinAtar-scale,
    clean-room re-implementation from the published game description — the
    reference only wraps full Atari ROMs via gym,
    rllib/env/wrappers/atari_wrappers.py, which cannot run inside XLA).

    Board: 3 rows of bricks (rows 1-3), paddle on the bottom row, a ball
    bouncing diagonally.  Actions: 0 noop, 1 left, 2 right.  Reward +1 per
    brick.  Episode ends when the ball passes the paddle (or at max_steps);
    clearing all bricks respawns them.  Observation: [10, 10, 4] float
    channels {paddle, ball, trail, bricks} — fed to a CNN trunk, which is
    what makes this the honest stand-in for the Atari PPO north star.
    Fully jittable: state is a flat pytree, all branching via jnp.where.
    """

    num_actions = 3
    obs_shape = (10, 10, 4)
    H = 10
    W = 10
    max_steps = 1000

    def reset(self, rng):
        k1, k2 = jax.random.split(rng)
        ball_x = jax.random.randint(k1, (), 0, self.W)
        dx = jnp.where(jax.random.bernoulli(k2), 1, -1).astype(jnp.int32)
        state = {
            "paddle_x": jnp.array(self.W // 2, jnp.int32),
            "ball_x": ball_x.astype(jnp.int32),
            "ball_y": jnp.array(4, jnp.int32),
            "dx": dx,
            "dy": jnp.array(1, jnp.int32),
            "last_x": ball_x.astype(jnp.int32),
            "last_y": jnp.array(3, jnp.int32),
            "bricks": jnp.ones((3, self.W), jnp.bool_),
            "t": jnp.zeros((), jnp.int32),
        }
        return state, self._obs(state)

    def _obs(self, s):
        obs = jnp.zeros(self.obs_shape, jnp.float32)
        obs = obs.at[self.H - 1, s["paddle_x"], 0].set(1.0)
        obs = obs.at[s["ball_y"], s["ball_x"], 1].set(1.0)
        obs = obs.at[s["last_y"], s["last_x"], 2].set(1.0)
        obs = obs.at[1:4, :, 3].set(s["bricks"].astype(jnp.float32))
        return obs

    def step(self, s, action, rng):
        paddle_x = jnp.clip(
            s["paddle_x"] - (action == 1) + (action == 2), 0, self.W - 1
        ).astype(jnp.int32)
        # Side-wall bounce.
        dx = jnp.where((s["ball_x"] + s["dx"] < 0)
                       | (s["ball_x"] + s["dx"] > self.W - 1),
                       -s["dx"], s["dx"])
        new_x = s["ball_x"] + dx
        # Ceiling bounce.
        dy = jnp.where(s["ball_y"] + s["dy"] < 0, -s["dy"], s["dy"])
        new_y = s["ball_y"] + dy
        # Brick hit: remove it, score, bounce back vertically.
        row = jnp.clip(new_y - 1, 0, 2)
        hit = (new_y >= 1) & (new_y <= 3) & s["bricks"][row, new_x]
        bricks = jnp.where(hit,
                           s["bricks"].at[row, new_x].set(False), s["bricks"])
        reward = jnp.where(hit, 1.0, 0.0)
        dy = jnp.where(hit, -dy, dy)
        new_y = jnp.where(hit, s["ball_y"], new_y)
        # Paddle row: catch bounces the ball up, a miss ends the episode.
        at_bottom = new_y >= self.H - 1
        caught = at_bottom & (new_x == paddle_x)
        dy = jnp.where(caught, jnp.array(-1, jnp.int32), dy)
        new_y = jnp.where(caught, self.H - 2, new_y)
        dead = at_bottom & ~caught
        # Cleared board respawns the bricks.
        bricks = jnp.where(bricks.any(), bricks, jnp.ones_like(bricks))
        t = s["t"] + 1
        done = dead | (t >= self.max_steps)
        new_state = {
            "paddle_x": paddle_x, "ball_x": new_x, "ball_y": new_y,
            "dx": dx, "dy": dy, "last_x": s["ball_x"], "last_y": s["ball_y"],
            "bricks": bricks, "t": t,
        }
        reset_state, reset_obs = self.reset(rng)
        out_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), reset_state, new_state)
        obs = jnp.where(done, reset_obs, self._obs(new_state))
        return out_state, obs, reward, done, {}


class SpaceInvaders:
    """Atari-class pixel Space Invaders on a 10x10 board (MinAtar-scale,
    clean-room from the published game description, like Breakout above).

    A cannon on the bottom row moves left/right and fires; a marching
    alien block descends one row each time it hits a side wall; random
    alive aliens drop bullets.  Reward +1 per alien shot.  Episode ends
    when an enemy bullet reaches the cannon, the aliens reach the bottom
    row, or at max_steps; a cleared wave respawns.  Observation:
    [10, 10, 4] float channels {cannon, aliens, friendly bullets, enemy
    bullets} — same CNN trunk as Breakout.  Actions: 0 noop, 1 left,
    2 right, 3 fire (cooldown-limited).  Fully jittable: flat pytree
    state, all branching via jnp.where.
    """

    num_actions = 4
    obs_shape = (10, 10, 4)
    H = 10
    W = 10
    max_steps = 1000
    move_interval = 4     # alien march period in env steps
    shot_cooldown = 4     # min steps between cannon shots
    enemy_fire_prob = 0.2

    def _initial_aliens(self):
        return jnp.zeros((self.H, self.W), jnp.bool_).at[1:5, 2:8].set(True)

    def reset(self, rng):
        state = {
            "pos": jnp.array(self.W // 2, jnp.int32),
            "aliens": self._initial_aliens(),
            "dir": jnp.array(1, jnp.int32),
            "move_t": jnp.zeros((), jnp.int32),
            "shot_t": jnp.zeros((), jnp.int32),
            "fbul": jnp.zeros((self.H, self.W), jnp.bool_),
            "ebul": jnp.zeros((self.H, self.W), jnp.bool_),
            "t": jnp.zeros((), jnp.int32),
        }
        return state, self._obs(state)

    def _obs(self, s):
        obs = jnp.zeros(self.obs_shape, jnp.float32)
        obs = obs.at[self.H - 1, s["pos"], 0].set(1.0)
        obs = obs.at[:, :, 1].set(s["aliens"].astype(jnp.float32))
        obs = obs.at[:, :, 2].set(s["fbul"].astype(jnp.float32))
        obs = obs.at[:, :, 3].set(s["ebul"].astype(jnp.float32))
        return obs

    @staticmethod
    def _shift_up(m):
        return jnp.concatenate([m[1:], jnp.zeros_like(m[:1])], axis=0)

    @staticmethod
    def _shift_down(m):
        return jnp.concatenate([jnp.zeros_like(m[:1]), m[:-1]], axis=0)

    @staticmethod
    def _shift_x(m, d):
        left = jnp.concatenate([m[:, 1:], jnp.zeros_like(m[:, :1])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(m[:, :1]), m[:, :-1]], axis=1)
        return jnp.where(d > 0, right, left)

    def step(self, s, action, rng):
        k_fire, k_col = jax.random.split(rng)
        pos = jnp.clip(s["pos"] - (action == 1) + (action == 2),
                       0, self.W - 1).astype(jnp.int32)
        # Bullets travel one cell per step; in-flight bullets move BEFORE
        # the new shot spawns, so a fresh bullet really starts at row H-2
        # (spawning first would advance it to H-3 on its spawn turn and
        # make aliens on row H-2 unhittable).
        fbul = self._shift_up(s["fbul"])
        ebul = self._shift_down(s["ebul"])
        # Cannon fire (cooldown-limited): bullet spawns above the cannon.
        can_fire = (action == 3) & (s["shot_t"] <= 0)
        fbul = fbul.at[self.H - 2, pos].max(can_fire)
        shot_t = jnp.where(can_fire, self.shot_cooldown,
                           jnp.maximum(s["shot_t"] - 1, 0)).astype(jnp.int32)
        # Alien march: sideways each interval; edge hit -> descend + flip.
        move_now = s["move_t"] + 1 >= self.move_interval
        aliens = s["aliens"]
        at_edge = jnp.where(s["dir"] > 0, aliens[:, -1].any(),
                            aliens[:, 0].any())
        descend = move_now & at_edge
        new_dir = jnp.where(descend, -s["dir"], s["dir"]).astype(jnp.int32)
        aliens = jnp.where(
            descend, self._shift_down(aliens),
            jnp.where(move_now, self._shift_x(aliens, s["dir"]), aliens))
        move_t = jnp.where(move_now, 0, s["move_t"] + 1).astype(jnp.int32)
        # A random alive alien drops a bullet.
        fire = jax.random.bernoulli(k_fire, self.enemy_fire_prob) \
            & aliens.any()
        flat_logits = jnp.where(aliens.reshape(-1), 0.0, -1e9)
        idx = jax.random.categorical(k_col, flat_logits)
        ebul = jnp.where(
            fire, ebul.at[idx // self.W, idx % self.W].set(True), ebul)
        # Friendly bullets hitting aliens: both vanish, +1 each.
        hits = fbul & aliens
        reward = jnp.sum(hits).astype(jnp.float32)
        aliens = aliens & ~hits
        fbul = fbul & ~hits
        # Death: enemy bullet on the cannon, or invasion reaches bottom.
        dead = ebul[self.H - 1, pos] | aliens[self.H - 1].any()
        # Cleared wave respawns.
        aliens = jnp.where(aliens.any(), aliens, self._initial_aliens())
        t = s["t"] + 1
        done = dead | (t >= self.max_steps)
        new_state = {"pos": pos, "aliens": aliens, "dir": new_dir,
                     "move_t": move_t, "shot_t": shot_t, "fbul": fbul,
                     "ebul": ebul, "t": t}
        reset_state, reset_obs = self.reset(rng)
        out_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), reset_state, new_state)
        obs = jnp.where(done, reset_obs, self._obs(new_state))
        return out_state, obs, reward, done, {}


class Breakout84:
    """Pixel Breakout at TRUE Atari resolution: [84, 84, 4] uint8 frames —
    the input size of the reference's Atari PPO north star
    (rllib/tuned_examples/ppo/atari-ppo.yaml:20, 84x84 wrap + 4-stack,
    rllib/env/wrappers/atari_wrappers.py:221).  The MinAtar-scale Breakout
    above keeps game logic on a 10x10 board; this env plays on the native
    84x84 pixel grid with multi-pixel sprites, so the policy network (the
    Nature CNN trunk) does the same per-frame work as on real Atari — the
    honest apples-to-apples benchmark input.

    Geometry: an 8x2-px paddle on the bottom rows moving +-3 px/action; a
    2x2-px ball with velocity (dx in {-2,-1,1,2}, dy in {-2,2}); a brick
    wall of 6 rows x 12 bricks (each 3x7 px) spanning rows 12..29.
    Channels {paddle, ball, trail, bricks} play the role of the 4-frame
    stack (trail gives motion, like frame differencing).  Reward +1 per
    brick; a missed ball ends the episode; a cleared wall respawns.
    Observations are uint8 {0, 255}: a 16k-env rollout buffer must not
    cost 4 bytes/pixel (the CNN trunk normalizes uint8 on entry).
    Fully jittable: dynamic_update_slice sprites, jnp.where branching.
    """

    num_actions = 3
    obs_shape = (84, 84, 4)
    H = W = 84
    PW = 8          # paddle width (px)
    PADDLE_ROW = 82  # paddle occupies rows 82..83
    BRICK_TOP = 12   # brick band rows 12..29 (6 brick-rows x 3 px)
    BRICK_H = 3
    BRICK_W = 7
    max_steps = 2500

    def reset(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        bx = jax.random.randint(k1, (), 8, self.W - 10).astype(jnp.int32)
        dx = jnp.take(jnp.array([-2, -1, 1, 2], jnp.int32),
                      jax.random.randint(k2, (), 0, 4))
        px = jax.random.randint(k3, (), 0, self.W - self.PW).astype(jnp.int32)
        state = {
            "px": px,
            "bx": bx, "by": jnp.array(40, jnp.int32),
            "dx": dx, "dy": jnp.array(2, jnp.int32),
            "lx": bx, "ly": jnp.array(38, jnp.int32),
            "bricks": jnp.ones((6, 12), jnp.bool_),
            "t": jnp.zeros((), jnp.int32),
        }
        return state, self._obs(state)

    def _obs(self, s):
        # Mask-based rendering (no scatter): sprites are outer products of
        # boolean row/col bands — vectorizes onto the VPU and fuses,
        # where per-env dynamic_update_slice scatters serialize (measured
        # the difference at ~3x whole-pipeline throughput at 2k envs).
        rows = jnp.arange(self.H, dtype=jnp.int32)
        cols = jnp.arange(self.W, dtype=jnp.int32)
        r = rows[:, None]
        c = cols[None, :]

        def sprite(top, left, h, w):
            return ((r >= top) & (r < top + h)
                    & (c >= left) & (c < left + w))

        paddle = sprite(self.PADDLE_ROW, s["px"], 2, self.PW)
        ball = sprite(s["by"], s["bx"], 2, 2)
        trail = sprite(s["ly"], s["lx"], 2, 2)
        # Brick channel: map each pixel to its brick cell and gather.
        brow = jnp.clip((rows - self.BRICK_TOP) // self.BRICK_H, 0, 5)
        bcol = jnp.clip(cols // self.BRICK_W, 0, 11)
        in_band = (rows >= self.BRICK_TOP) \
            & (rows < self.BRICK_TOP + 6 * self.BRICK_H)
        wall = s["bricks"][brow[:, None], bcol[None, :]] & in_band[:, None]
        stacked = jnp.stack([paddle, ball, trail, wall], axis=-1)
        return (stacked * jnp.uint8(255)).astype(jnp.uint8)

    def step(self, s, action, rng):
        px = jnp.clip(s["px"] - 3 * (action == 1) + 3 * (action == 2),
                      0, self.W - self.PW).astype(jnp.int32)
        # Side walls bounce (ball is 2px wide).
        dx = jnp.where((s["bx"] + s["dx"] < 0)
                       | (s["bx"] + s["dx"] > self.W - 2),
                       -s["dx"], s["dx"])
        new_x = jnp.clip(s["bx"] + dx, 0, self.W - 2)
        # Ceiling bounce.
        dy = jnp.where(s["by"] + s["dy"] < 0, -s["dy"], s["dy"])
        new_y = jnp.clip(s["by"] + dy, 0, self.H - 2)
        # Brick collision on the landing cell.
        in_band = (new_y >= self.BRICK_TOP) \
            & (new_y < self.BRICK_TOP + 6 * self.BRICK_H)
        row = jnp.clip((new_y - self.BRICK_TOP) // self.BRICK_H, 0, 5)
        col = jnp.clip((new_x + 1) // self.BRICK_W, 0, 11)
        hit = in_band & s["bricks"][row, col]
        bricks = jnp.where(hit, s["bricks"].at[row, col].set(False),
                           s["bricks"])
        reward = jnp.where(hit, 1.0, 0.0)
        dy = jnp.where(hit, -dy, dy)
        new_y = jnp.where(hit, s["by"], new_y)
        # Paddle band: catch bounces up, a miss ends the episode.
        at_bottom = new_y >= self.PADDLE_ROW - 1
        caught = at_bottom & (new_x + 1 >= px) & (new_x <= px + self.PW - 1)
        dy = jnp.where(caught, -jnp.abs(dy), dy)
        new_y = jnp.where(caught,
                          jnp.array(self.PADDLE_ROW - 3, jnp.int32), new_y)
        dead = at_bottom & ~caught
        bricks = jnp.where(bricks.any(), bricks, jnp.ones_like(bricks))
        t = s["t"] + 1
        done = dead | (t >= self.max_steps)
        new_state = {
            "px": px, "bx": new_x, "by": new_y, "dx": dx, "dy": dy,
            "lx": s["bx"], "ly": s["by"], "bricks": bricks, "t": t,
        }
        reset_state, reset_obs = self.reset(rng)
        out_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), reset_state, new_state)
        obs = jnp.where(done, reset_obs, self._obs(new_state))
        return out_state, obs, reward, done, {}


class StatelessCartPole(CartPole):
    """CartPole with the velocity components hidden (obs = [x, theta]) —
    the classic recurrent-policy testbed: a memoryless policy cannot infer
    which way the pole is moving (reference:
    rllib/examples/env/stateless_cartpole.py, re-derived for the jittable
    env)."""

    obs_dim = 2

    def _obs(self, core):
        return core[jnp.array([0, 2])]  # x, theta — drop the velocities


class PendulumContinuous(Pendulum):
    """Pendulum-v1 with the real continuous torque action — the SAC-family
    env.  ``action`` is a float array of shape [action_dim] in
    [-max_torque, max_torque] (reference env semantics:
    gym Pendulum-v1; the discretized parent serves categorical policies)."""

    num_actions = None  # continuous
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def _torque(self, action):
        return jnp.clip(jnp.reshape(action, ()), self.action_low,
                        self.action_high)


REGISTRY = {
    "CartPole-v1": CartPole,
    "StatelessCartPole-v1": StatelessCartPole,
    "Pendulum-v1": Pendulum,
    "PendulumContinuous-v1": PendulumContinuous,
    "Breakout-MinAtar-v0": Breakout,
    "Breakout-Atari84-v0": Breakout84,
    "SpaceInvaders-MinAtar-v0": SpaceInvaders,
}


def make_jax_env(name: str):
    if name not in REGISTRY:
        raise ValueError(f"unknown jax env {name!r}; have {list(REGISTRY)}")
    return REGISTRY[name]()


def vector_reset(env, rng, num_envs: int):
    """Batched reset: returns (states, obs) with leading [num_envs]."""
    return jax.vmap(env.reset)(jax.random.split(rng, num_envs))


def vector_step(env, states, actions, rng):
    num = actions.shape[0]
    return jax.vmap(env.step)(states, actions, jax.random.split(rng, num))
