"""JAX-native vectorized environments.

No equivalent exists in the reference: RLlib steps Python gym envs on CPU
rollout workers (rllib/evaluation/sampler.py).  The TPU-native design
additionally runs envs *inside the compiled program* (Podracer/Anakin
architecture, PAPERS.md) — thousands of env instances as a batched state
pytree, stepped by lax.scan on device, so rollout+learn is one jit with no
host↔device traffic.  CPU-actor rollouts (py_envs.py) remain for envs that
can't be expressed in JAX.

Env contract (functional, vmap/scan-safe):
    reset(rng) -> (state, obs)
    step(state, action, rng) -> (state, obs, reward, done, info)
Auto-reset on done is built into step (standard Anakin practice).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


class CartPole:
    """CartPole-v1 dynamics (matches the classic gym spec: 500-step limit,
    ±2.4 position, ±12° angle)."""

    num_actions = 2
    obs_dim = 4

    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 2 * jnp.pi / 360
    x_threshold = 2.4
    max_steps = 500

    def reset(self, rng) -> Tuple[Any, jax.Array]:
        core = jax.random.uniform(rng, (4,), minval=-0.05, maxval=0.05)
        state = {"core": core, "t": jnp.zeros((), jnp.int32)}
        return state, core

    def step(self, state, action, rng):
        x, x_dot, theta, theta_dot = state["core"]
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        core = jnp.stack([x, x_dot, theta, theta_dot])
        t = state["t"] + 1
        done = (
            (jnp.abs(x) > self.x_threshold)
            | (jnp.abs(theta) > self.theta_threshold)
            | (t >= self.max_steps)
        )
        reward = jnp.ones(())
        # Auto-reset.
        reset_state, reset_obs = self.reset(rng)
        new_state = {
            "core": jnp.where(done, reset_state["core"], core),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs = jnp.where(done, reset_obs, core)
        return new_state, obs, reward, done, {}


class Pendulum:
    """Pendulum-v1 with 3-bin discretized torque (keeps one categorical
    policy head across envs; continuous head lands with the SAC family)."""

    num_actions = 3
    obs_dim = 3
    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    l = 1.0
    max_steps = 200

    def reset(self, rng):
        k1, k2 = jax.random.split(rng)
        th = jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi)
        thdot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        state = {"th": th, "thdot": thdot, "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(state)

    def _obs(self, state):
        return jnp.stack([jnp.cos(state["th"]), jnp.sin(state["th"]),
                          state["thdot"]])

    def step(self, state, action, rng):
        u = (action.astype(jnp.float32) - 1.0) * self.max_torque
        th, thdot = state["th"], state["thdot"]
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.l) * jnp.sin(th)
                         + 3.0 / (self.m * self.l ** 2) * u) * self.dt
        thdot = jnp.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        t = state["t"] + 1
        done = t >= self.max_steps
        reset_state, reset_obs = self.reset(rng)
        new_state = {
            "th": jnp.where(done, reset_state["th"], th),
            "thdot": jnp.where(done, reset_state["thdot"], thdot),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs_next = self._obs({"th": th, "thdot": thdot})
        obs = jnp.where(done, reset_obs, obs_next)
        return new_state, obs, -cost, done, {}


REGISTRY = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
}


def make_jax_env(name: str):
    if name not in REGISTRY:
        raise ValueError(f"unknown jax env {name!r}; have {list(REGISTRY)}")
    return REGISTRY[name]()


def vector_reset(env, rng, num_envs: int):
    """Batched reset: returns (states, obs) with leading [num_envs]."""
    return jax.vmap(env.reset)(jax.random.split(rng, num_envs))


def vector_step(env, states, actions, rng):
    num = actions.shape[0]
    return jax.vmap(env.step)(states, actions, jax.random.split(rng, num))
