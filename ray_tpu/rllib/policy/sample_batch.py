"""SampleBatch: the RL data container (reference:
rllib/policy/sample_batch.py:96; MultiAgentBatch :1218)."""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

# Canonical columns (reference SampleBatch.OBS etc.)
OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "new_obs"
VF_PREDS = "vf_preds"
ACTION_LOGP = "action_logp"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"


class SampleBatch(dict):
    """Dict of equally-long numpy arrays."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def gather(refs: List[Any]) -> List["SampleBatch"]:
        """Fetch a burst of SampleBatch ObjectRefs with ONE batched
        resolve round trip (ray_tpu.get_many) instead of one head
        request per ref — the rollout-gather hot path."""
        import ray_tpu

        return ray_tpu.get_many(refs)

    @staticmethod
    def gather_concat(refs: List[Any]) -> "SampleBatch":
        """gather() + concat into one training batch."""
        return SampleBatch.concat_samples(SampleBatch.gather(refs))

    @staticmethod
    def _check_columns(batches: List["SampleBatch"]) -> set:
        keys = set(batches[0].keys())
        for b in batches[1:]:
            if set(b.keys()) != keys:
                # Loud, not silent: dropping the odd column loses training
                # data; indexing it would KeyError mid-concatenate.
                raise ValueError(
                    "concat_samples requires identical columns; got "
                    f"{sorted(keys)} vs {sorted(b.keys())}")
        return keys

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = SampleBatch._check_columns(batches)
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches]) for k in keys})

    @staticmethod
    def concat_samples_into(batches: List["SampleBatch"],
                            out: Optional["SampleBatch"]) -> "SampleBatch":
        """``concat_samples`` with destination reuse: when ``out`` (a
        previous result) already has matching shapes/dtypes, fragment rows
        are copied into its arrays instead of allocating a fresh batch —
        the streaming consumer concatenates one train batch per iteration,
        so reuse removes a full batch-sized allocation + GC churn from the
        per-iteration hot path.  The caller must be done with ``out``'s
        previous contents (the learner has consumed them)."""
        if not batches:
            return SampleBatch()
        keys = SampleBatch._check_columns(batches)
        total = sum(len(b) for b in batches)
        result: Dict[str, np.ndarray] = {}
        for k in keys:
            first = np.asarray(batches[0][k])
            shape = (total,) + first.shape[1:]
            dst = None
            if out is not None:
                prev = out.get(k)
                if prev is not None and prev.shape == shape \
                        and prev.dtype == first.dtype:
                    dst = prev
            if dst is None:
                dst = np.empty(shape, first.dtype)
            pos = 0
            for b in batches:
                arr = b[k]
                dst[pos:pos + len(arr)] = arr
                pos += len(arr)
            result[k] = dst
        return SampleBatch(result)

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        idx = np.random.default_rng(seed).permutation(len(self))
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        for s in range(0, len(self) - size + 1, size):
            yield self.slice(s, s + size)

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        ids = self[EPS_ID]
        boundaries = np.flatnonzero(np.diff(ids)) + 1
        start = 0
        for b in list(boundaries) + [len(self)]:
            out.append(self.slice(start, b))
            start = b
        return out

    def as_jax(self, device=None):
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in self.items()}

    # ---- sequence support (reference: SampleBatch.seq_lens +
    # rllib/policy/rnn_sequencing.py pad_batch_to_sequences_of_same_size) ----
    def to_sequences(self, max_seq_len: int,
                     states: Optional[List[str]] = None
                     ) -> "SampleBatch":
        """Chunk episodes into sequences of <= max_seq_len, pad to the
        fixed length, and add a ``seq_lens`` column.  Output columns have
        shape [num_seqs, max_seq_len, ...] (zero-padded); state columns
        (if named) keep only each sequence's FIRST row ([num_seqs, ...]) —
        the reference's state_in semantics.  The fixed [S, T, ...] layout
        is what a jit-compiled recurrent loss wants: one compilation for
        every batch."""
        states = states or []
        seqs: List[SampleBatch] = []
        for ep in self.split_by_episode():
            for s in range(0, len(ep), max_seq_len):
                seqs.append(ep.slice(s, min(s + max_seq_len, len(ep))))
        if not seqs or all(len(sq) == 0 for sq in seqs):
            # Keep the schema: empty [0, T, ...] columns compose with
            # non-empty sequence batches (concat) instead of key-erroring.
            out = {}
            for k, v in self.items():
                v = np.asarray(v)
                out[k] = (np.zeros((0,) + v.shape[1:], v.dtype)
                          if k in states else
                          np.zeros((0, max_seq_len) + v.shape[1:], v.dtype))
            out["seq_lens"] = np.zeros((0,), np.int32)
            return SampleBatch(out)
        out: Dict[str, np.ndarray] = {}
        for k in seqs[0].keys():
            if k in states:
                out[k] = np.stack([sq[k][0] for sq in seqs])
                continue
            first = np.asarray(seqs[0][k])
            padded = np.zeros((len(seqs), max_seq_len) + first.shape[1:],
                              first.dtype)
            for i, sq in enumerate(seqs):
                padded[i, : len(sq)] = sq[k]
            out[k] = padded
        out["seq_lens"] = np.asarray([len(sq) for sq in seqs], np.int32)
        return SampleBatch(out)

    @staticmethod
    def sequence_mask(seq_lens: np.ndarray, max_seq_len: int) -> np.ndarray:
        """[S, T] 0/1 mask from seq_lens — multiply into per-step losses
        so padding contributes nothing."""
        return (np.arange(max_seq_len)[None, :]
                < np.asarray(seq_lens)[:, None]).astype(np.float32)


class MultiAgentBatch:
    """Per-policy batches (reference: policy/sample_batch.py
    MultiAgentBatch — concat, timeslice, and the agent→policy grouping
    builder the rollout path uses)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch], env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    @staticmethod
    def from_agent_batches(agent_batches: Dict[Any, SampleBatch],
                           policy_mapping_fn: Callable[[Any], str],
                           env_steps: int) -> "MultiAgentBatch":
        """Group per-agent batches under their policies (the
        policy_mapping_fn contract; shared-policy training maps every
        agent to one id)."""
        grouped: Dict[str, List[SampleBatch]] = {}
        for agent_id, batch in agent_batches.items():
            grouped.setdefault(policy_mapping_fn(agent_id), []).append(batch)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs)
             for pid, bs in grouped.items()}, env_steps)

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]
                       ) -> "MultiAgentBatch":
        policies: Dict[str, List[SampleBatch]] = {}
        steps = 0
        for mb in batches:
            steps += mb.env_steps()
            for pid, b in mb.policy_batches.items():
                policies.setdefault(pid, []).append(b)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs)
             for pid, bs in policies.items()}, steps)

    def __repr__(self):
        sizes = {p: len(b) for p, b in self.policy_batches.items()}
        return f"MultiAgentBatch(env_steps={self._env_steps}, {sizes})"
