"""SampleBatch: the RL data container (reference:
rllib/policy/sample_batch.py:96; MultiAgentBatch :1218)."""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

# Canonical columns (reference SampleBatch.OBS etc.)
OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "new_obs"
VF_PREDS = "vf_preds"
ACTION_LOGP = "action_logp"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"


class SampleBatch(dict):
    """Dict of equally-long numpy arrays."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches]) for k in keys})

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        idx = np.random.default_rng(seed).permutation(len(self))
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        for s in range(0, len(self) - size + 1, size):
            yield self.slice(s, s + size)

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        ids = self[EPS_ID]
        boundaries = np.flatnonzero(np.diff(ids)) + 1
        start = 0
        for b in list(boundaries) + [len(self)]:
            out.append(self.slice(start, b))
            start = b
        return out

    def as_jax(self, device=None):
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in self.items()}


class MultiAgentBatch:
    def __init__(self, policy_batches: Dict[str, SampleBatch], env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())
