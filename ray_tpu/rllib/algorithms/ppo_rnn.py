"""Recurrent (LSTM) PPO, anakin-style.

Reference: the use_lstm/lstm_cell_size model path (rllib model config,
models/catalog.py MODEL_DEFAULTS; torch RNN wrapper
models/torch/recurrent_net.py) plus PPO's sequence handling (SampleBatch
seq_lens + state_in/state_out columns).

TPU redesign: no padding or seq_lens at all.  The rollout is a [T, N]
scan that carries the LSTM state on device, resetting per-env state at
episode boundaries; training replays the SAME scan from the unroll's
initial carry, so hidden states are exact (the reference approximates
with stored state_in at fragment boundaries).  Minibatches cut across the
ENV axis (whole sequences stay intact) — the recurrent analogue of the
reference's sequence-preserving minibatching, without padding because
every sequence has length T by construction.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.mlp import MLP
from ray_tpu.rllib.evaluation.postprocessing import gae_jax
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step


class RecurrentActorCritic(nn.Module):
    """Per-head embed → LSTM → head, with SEPARATE recurrent trunks for
    policy and value — matching the feedforward module's separate trunks
    (core/rl_module.py DiscreteActorCritic): a shared trunk lets the large
    early value-error gradients wreck the policy representation.  Exposed
    as a single per-step function; sequences scan it from outside so the
    same params serve rollout and training.  Pixel envs (obs_shape set)
    embed each frame through a CNN first (reference: visionnet + LSTM
    wrapper, models/torch/recurrent_net.py)."""

    num_actions: int
    hiddens: Tuple[int, ...] = (64,)
    lstm_size: int = 128
    obs_shape: Optional[Tuple[int, ...]] = None  # set for pixel obs

    @nn.compact
    def __call__(self, carry, obs, reset):
        """One step: zero both carries where `reset`, then advance.
        carry: ((c,h) policy, (c,h) value), each [N, lstm]; reset [N];
        obs [N, D] flat or [N, H, W, C] pixels."""
        mask = (1.0 - reset.astype(jnp.float32))[:, None]

        def embed(name):
            if self.obs_shape is None:
                return MLP(self.hiddens, self.lstm_size,
                           name=f"embed_{name}")(obs)
            from ray_tpu.models.nature_cnn import MinAtarCNN, NatureCNN

            small = min(self.obs_shape[0], self.obs_shape[1]) < 32
            cnn = (MinAtarCNN(out_dim=self.lstm_size, name=f"cnn_{name}")
                   if small else
                   NatureCNN(out_dim=self.lstm_size, name=f"cnn_{name}"))
            return cnn(obs)

        def trunk(sub_carry, name):
            c, h = sub_carry
            c, h = c * mask, h * mask
            x = embed(name)
            return nn.OptimizedLSTMCell(self.lstm_size,
                                        name=f"lstm_{name}")((c, h), x)

        pi_carry, y_pi = trunk(carry[0], "pi")
        vf_carry, y_vf = trunk(carry[1], "vf")
        logits = nn.Dense(self.num_actions, name="pi")(y_pi)
        value = nn.Dense(1, name="vf")(y_vf)[..., 0]
        return (pi_carry, vf_carry), logits, value


def zero_carry(n: int, lstm_size: int):
    one = (jnp.zeros((n, lstm_size)), jnp.zeros((n, lstm_size)))
    return (one, one)


def make_rnn_eval_rollout(env, module, lstm_size: int,
                          num_eval_envs: int = 16):
    """Greedy in-env rollout threading the LSTM carry — the recurrent
    analogue of bc.make_greedy_eval_rollout (used by Algorithm.evaluate
    / the `rllib evaluate` CLI)."""

    def eval_rollout(params, key, num_steps: int):
        k_env, k_run = jax.random.split(key)
        env_states, obs = vector_reset(env, k_env, num_eval_envs)

        def step(carry_all, _):
            (env_states, obs, carry, prev_done, rng, ep_ret, dsum,
             dcnt) = carry_all
            rng, k_s = jax.random.split(rng)
            carry, logits, _ = module.apply(params, carry, obs, prev_done)
            action = jnp.argmax(logits, axis=-1)
            env_states, obs, reward, done, _ = vector_step(
                env, env_states, action, k_s)
            ep_ret = ep_ret + reward
            dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
            dcnt = dcnt + jnp.sum(done)
            ep_ret = jnp.where(done, 0.0, ep_ret)
            return (env_states, obs, carry, done, rng, ep_ret, dsum,
                    dcnt), None

        carry = (env_states, obs, zero_carry(num_eval_envs, lstm_size),
                 jnp.zeros(num_eval_envs, bool), k_run,
                 jnp.zeros(num_eval_envs), jnp.zeros(()), jnp.zeros(()))
        carry, _ = jax.lax.scan(step, carry, None, length=num_steps)
        dsum, dcnt = carry[-2], carry[-1]
        return dsum / jnp.maximum(dcnt, 1.0)

    return jax.jit(eval_rollout, static_argnums=2)


class RNNAnakinState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array
    carry: Tuple[jax.Array, jax.Array]
    prev_done: jax.Array           # [N] — reset mask for the NEXT step
    rng: jax.Array
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array


def make_anakin_ppo_rnn(config):
    """Builds (module, init_fn, jitted train_step, steps/iter) for
    LSTM-PPO; mirrors make_anakin_ppo with state threading."""
    from ray_tpu.rllib.algorithms.ppo import ppo_surrogate

    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    if getattr(env, "obs_shape", None) is not None:
        raise ValueError(
            "use_lstm supports flat-observation envs only (a CNN+LSTM "
            "trunk is not wired yet); got pixel env "
            f"{config.env!r} with obs_shape={env.obs_shape}")
    if env.num_actions is None:
        raise ValueError(
            "use_lstm supports discrete action spaces only; continuous "
            f"env {config.env!r} belongs to the SAC family")
    module = RecurrentActorCritic(num_actions=env.num_actions,
                                  hiddens=tuple(config.hiddens),
                                  lstm_size=config.lstm_cell_size)
    tx_parts = []
    if config.grad_clip:
        tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
    tx_parts.append(optax.adam(config.lr))
    tx = optax.chain(*tx_parts)

    N, T = config.num_envs, config.unroll_length
    # Minibatches cut across envs: sequences stay whole.
    envs_per_mb = max(1, min(N, config.sgd_minibatch_size // max(T, 1)))
    num_mb = N // envs_per_mb
    if N % envs_per_mb:
        raise ValueError(
            f"num_envs={N} is not divisible by the per-minibatch env count "
            f"{envs_per_mb} (sgd_minibatch_size={config.sgd_minibatch_size}"
            f" / unroll_length={T}): {N - num_mb * envs_per_mb} whole env "
            "sequences would be silently dropped from every SGD epoch — "
            "pick num_envs divisible by envs-per-minibatch")

    def init_fn(seed: int = 0) -> RNNAnakinState:
        rng = jax.random.PRNGKey(seed)
        rng, k_init, k_env = jax.random.split(rng, 3)
        env_states, obs = vector_reset(env, k_env, N)
        carry = zero_carry(N, config.lstm_cell_size)
        params = module.init(k_init, carry, obs, jnp.zeros(N, bool))
        return RNNAnakinState(params, tx.init(params), env_states, obs,
                              carry, jnp.zeros(N, bool), rng,
                              jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    def rollout_step(carry_all, _):
        (params, env_states, obs, carry, prev_done, rng, ep_ret, dsum,
         dcnt) = carry_all
        rng, k_act, k_step = jax.random.split(rng, 3)
        carry, logits, value = module.apply(params, carry, obs, prev_done)
        action = jax.random.categorical(k_act, logits)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, action[:, None], -1)[:, 0]
        env_states, next_obs, reward, done, _ = vector_step(
            env, env_states, action, k_step)
        ep_ret = ep_ret + reward
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = (obs, prev_done, action, logp, value, reward, done)
        return (params, env_states, next_obs, carry, done, rng, ep_ret,
                dsum, dcnt), out

    def sequence_forward(params, carry0, obs_t, reset_t, actions_t):
        """Replay the scan for training: exact hidden states, no padding.
        obs_t [T, n, d], reset_t [T, n], actions_t [T, n]."""
        def f(carry, inp):
            obs, reset, act = inp
            carry, logits, value = module.apply(params, carry, obs, reset)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, act[:, None], -1)[:, 0]
            ent = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            return carry, (logp, value, ent)

        _, (logp, value, ent) = jax.lax.scan(
            f, carry0, (obs_t, reset_t, actions_t))
        return logp, value, ent

    def seq_ppo_loss(params, batch):
        logp, value, entropy = sequence_forward(
            params, batch["carry0"], batch["obs"], batch["resets"],
            batch["actions"])
        return ppo_surrogate(logp, value, entropy, batch,
                             clip_param=config.clip_param,
                             vf_clip_param=config.vf_clip_param,
                             vf_loss_coeff=config.vf_loss_coeff,
                             entropy_coeff=config.entropy_coeff)

    def train_step(state: RNNAnakinState
                   ) -> Tuple[RNNAnakinState, Dict[str, jax.Array]]:
        carry0 = state.carry  # hidden state at the unroll's first step
        roll = (state.params, state.env_states, state.obs, state.carry,
                state.prev_done, state.rng, state.ep_return,
                state.done_return_sum, state.done_count)
        roll, traj = jax.lax.scan(rollout_step, roll, None, length=T)
        (params, env_states, obs, carry, prev_done, rng, ep_ret, dsum,
         dcnt) = roll
        obs_t, reset_t, act_t, logp_t, val_t, rew_t, done_t = traj

        _, _, last_value = module.apply(params, carry, obs, prev_done)
        adv, vtarg = gae_jax(rew_t, val_t, done_t, last_value,
                             config.gamma, config.lambda_)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        from ray_tpu.rllib.algorithms.ppo import run_ppo_sgd

        def make_mb(env_idx):
            # Minibatches cut across the ENV axis: whole sequences intact.
            return {
                "carry0": jax.tree_util.tree_map(
                    lambda c: c[env_idx], carry0),
                "obs": obs_t[:, env_idx],
                "resets": reset_t[:, env_idx],
                "actions": act_t[:, env_idx],
                "action_logp": logp_t[:, env_idx],
                "advantages": adv[:, env_idx],
                "value_targets": vtarg[:, env_idx],
            }

        (params, opt_state, rng), (losses, auxes) = run_ppo_sgd(
            params, state.opt_state, rng, seq_ppo_loss, make_mb,
            N, envs_per_mb, num_mb, config.num_sgd_iter, tx)

        new_state = RNNAnakinState(params, opt_state, env_states, obs,
                                   carry, prev_done, rng, ep_ret, dsum,
                                   dcnt)
        metrics = {
            "total_loss": losses.mean(),
            "policy_loss": auxes["policy_loss"].mean(),
            "vf_loss": auxes["vf_loss"].mean(),
            "entropy": auxes["entropy"].mean(),
            "episode_return_sum": dsum,
            "episode_count": dcnt,
        }
        return new_state, metrics

    return module, init_fn, jax.jit(train_step), N * T
