"""Attention-memory (GTrXL-style) PPO, anakin-style.

Reference: the use_attention model path (rllib model config
use_attention/attention_dim/attention_num_transformer_units etc.,
models/catalog.py MODEL_DEFAULTS; torch GTrXL
models/torch/attention_net.py — gated transformer-XL blocks over a
memory of past inputs, per Parisotto et al.'s "Stabilizing Transformers
for RL").

TPU redesign: instead of the reference's recurrent memory tensors
(state_in/state_out columns + view-requirement machinery), the policy
attends over a fixed sliding WINDOW of the last K observations carried
on device through the rollout scan (cleared at episode boundaries).
That makes training feedforward — each timestep's forward depends only
on its own window, so minibatches are arbitrary flat slices like
vanilla PPO: no sequence replay, no seq_lens, no padding.  The blocks
are GTrXL's: pre-LayerNorm attention/MLP with GRU-type gates biased
toward the identity skip, which is what makes transformer policies
trainable with RL gradients.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.evaluation.postprocessing import gae_jax
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step


class GRUGate(nn.Module):
    """GTrXL's GRU-style residual gate; bias > 0 on the update gate makes
    the block start as (near-)identity, the paper's key stabilizer."""

    d: int
    bias: float = 2.0

    @nn.compact
    def __call__(self, x, y):
        # x: the residual stream, y: the transformed candidate.
        r = nn.sigmoid(nn.Dense(self.d, use_bias=False, name="wr")(y)
                       + nn.Dense(self.d, use_bias=False, name="ur")(x))
        z = nn.sigmoid(nn.Dense(self.d, use_bias=False, name="wz")(y)
                       + nn.Dense(self.d, use_bias=False, name="uz")(x)
                       - self.bias)
        h = nn.tanh(nn.Dense(self.d, use_bias=False, name="wh")(y)
                    + nn.Dense(self.d, use_bias=False, name="uh")(r * x))
        return (1 - z) * x + z * h


class GTrXLBlock(nn.Module):
    d: int
    heads: int

    @nn.compact
    def __call__(self, x, mask):
        h = nn.LayerNorm()(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, qkv_features=self.d, name="mha")(
                h, h, mask=mask)
        x = GRUGate(self.d, name="gate_attn")(x, h)
        h = nn.LayerNorm()(x)
        h = nn.Dense(4 * self.d, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d, name="mlp_out")(h)
        return GRUGate(self.d, name="gate_mlp")(x, h)


class AttentionActorCritic(nn.Module):
    """Window of K observations → separate GTrXL trunks → heads (separate
    pi/vf trunks for the same reason the LSTM module uses them: early
    value-error gradients wreck a shared representation).  Pixel envs
    (obs_shape set) run each window slot through a CNN encoder before
    the attention stack — the CNN+attention combination the reference
    builds with visionnet + GTrXL."""

    num_actions: int
    window: int
    d_model: int = 64
    heads: int = 4
    layers: int = 1
    obs_shape: Optional[Tuple[int, ...]] = None  # set for pixel windows

    @nn.compact
    def __call__(self, obs_win, valid):
        """obs_win [B, K, obs_dim] (flat) or [B, K, H, W, C] (pixels);
        valid [B, K] bool (False = empty slot after an episode boundary).
        Returns (logits [B, A], value [B])."""
        K = self.window
        causal = jnp.tril(jnp.ones((K, K), bool))
        # Rows may only attend to valid columns (and themselves via the
        # diagonal, which is always valid: slot K-1 holds the current obs).
        mask = causal[None, None] & valid[:, None, None, :]

        def embed(tag):
            if self.obs_shape is None:
                return nn.Dense(self.d_model, name=f"embed_{tag}")(obs_win)
            from ray_tpu.models.nature_cnn import MinAtarCNN, NatureCNN

            B = obs_win.shape[0]
            frames = obs_win.reshape((B * K,) + tuple(self.obs_shape))
            small = min(self.obs_shape[0], self.obs_shape[1]) < 32
            cnn = (MinAtarCNN(out_dim=self.d_model, name=f"cnn_{tag}")
                   if small else
                   NatureCNN(out_dim=self.d_model, name=f"cnn_{tag}"))
            return cnn(frames).reshape(B, K, self.d_model)

        def trunk(tag):
            x = embed(tag)
            x = x + self.param(f"pos_{tag}",
                               nn.initializers.normal(0.02),
                               (K, self.d_model))
            for i in range(self.layers):
                x = GTrXLBlock(self.d_model, self.heads,
                               name=f"block_{tag}_{i}")(x, mask)
            return x[:, -1]

        logits = nn.Dense(self.num_actions, name="pi")(trunk("pi"))
        value = nn.Dense(1, name="vf")(trunk("vf"))[..., 0]
        return logits, value


def make_attn_eval_rollout(env, module, window: int,
                           num_eval_envs: int = 16):
    """Greedy in-env rollout threading the observation window — the
    attention-policy analogue of bc.make_greedy_eval_rollout (used by
    Algorithm.evaluate / the `rllib evaluate` CLI)."""

    obs_shape = getattr(env, "obs_shape", None)
    obs_dims = tuple(obs_shape) if obs_shape is not None else (env.obs_dim,)

    def eval_rollout(params, key, num_steps: int):
        k_env, k_run = jax.random.split(key)
        env_states, obs = vector_reset(env, k_env, num_eval_envs)

        def step(carry, _):
            (env_states, obs, hist, valid, prev_done, rng, ep_ret, dsum,
             dcnt) = carry
            rng, k_s = jax.random.split(rng)
            keep = ~prev_done
            hist = hist * keep.reshape(
                (num_eval_envs,) + (1,) * (hist.ndim - 1))
            valid = valid & keep[:, None]
            hist = jnp.concatenate([hist[:, 1:], obs[:, None]], axis=1)
            valid = jnp.concatenate(
                [valid[:, 1:], jnp.ones((num_eval_envs, 1), bool)], axis=1)
            logits, _ = module.apply(params, hist, valid)
            action = jnp.argmax(logits, axis=-1)
            env_states, obs, reward, done, _ = vector_step(
                env, env_states, action, k_s)
            ep_ret = ep_ret + reward
            dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
            dcnt = dcnt + jnp.sum(done)
            ep_ret = jnp.where(done, 0.0, ep_ret)
            return (env_states, obs, hist, valid, done, rng, ep_ret,
                    dsum, dcnt), None

        carry = (env_states, obs,
                 jnp.zeros((num_eval_envs, window) + obs_dims),
                 jnp.zeros((num_eval_envs, window), bool),
                 jnp.zeros(num_eval_envs, bool), k_run,
                 jnp.zeros(num_eval_envs), jnp.zeros(()), jnp.zeros(()))
        carry, _ = jax.lax.scan(step, carry, None, length=num_steps)
        dsum, dcnt = carry[-2], carry[-1]
        return dsum / jnp.maximum(dcnt, 1.0)

    return jax.jit(eval_rollout, static_argnums=2)


class AttnAnakinState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array
    hist: jax.Array       # [N, K, obs_dim] sliding window (newest last)
    valid: jax.Array      # [N, K] bool
    prev_done: jax.Array  # [N] — clear the window before the NEXT step
    rng: jax.Array
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array


def make_anakin_ppo_attn(config):
    """Builds (module, init_fn, jitted train_step, steps/iter) for
    attention-memory PPO; mirrors make_anakin_ppo with window threading."""
    from ray_tpu.rllib.algorithms.ppo import ppo_surrogate

    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    if env.num_actions is None:
        raise ValueError(
            "use_attention supports discrete action spaces only; "
            f"continuous env {config.env!r} belongs to the SAC family")
    obs_shape = getattr(env, "obs_shape", None)
    obs_dims = tuple(obs_shape) if obs_shape is not None else (env.obs_dim,)
    K = config.attention_window
    module = AttentionActorCritic(
        num_actions=env.num_actions, window=K,
        d_model=config.attention_dim, heads=config.attention_num_heads,
        layers=config.attention_num_layers,
        obs_shape=tuple(obs_shape) if obs_shape is not None else None)
    tx_parts = []
    if config.grad_clip:
        tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
    tx_parts.append(optax.adam(config.lr))
    tx = optax.chain(*tx_parts)

    N, T = config.num_envs, config.unroll_length
    batch_total = N * T
    mb_size = min(config.sgd_minibatch_size, batch_total)
    num_mb = batch_total // mb_size

    def push(hist, valid, obs, prev_done):
        """Clear windows of just-reset envs, then append the current obs
        into slot K-1 (obs may be flat [N, D] or pixels [N, H, W, C])."""
        keep = ~prev_done
        hist = hist * keep.reshape((N,) + (1,) * (hist.ndim - 1))
        valid = valid & keep[:, None]
        hist = jnp.concatenate([hist[:, 1:], obs[:, None]], axis=1)
        valid = jnp.concatenate(
            [valid[:, 1:], jnp.ones((N, 1), bool)], axis=1)
        return hist, valid

    def init_fn(seed: int = 0) -> AttnAnakinState:
        rng = jax.random.PRNGKey(seed)
        rng, k_init, k_env = jax.random.split(rng, 3)
        env_states, obs = vector_reset(env, k_env, N)
        hist = jnp.zeros((N, K) + obs_dims)
        valid = jnp.zeros((N, K), bool)
        params = module.init(k_init, hist, valid)
        return AttnAnakinState(params, tx.init(params), env_states, obs,
                               hist, valid, jnp.zeros(N, bool), rng,
                               jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    def rollout_step(carry, _):
        (params, env_states, obs, hist, valid, prev_done, rng, ep_ret,
         dsum, dcnt) = carry
        rng, k_act, k_step = jax.random.split(rng, 3)
        hist, valid = push(hist, valid, obs, prev_done)
        logits, value = module.apply(params, hist, valid)
        action = jax.random.categorical(k_act, logits)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, action[:, None], -1)[:, 0]
        env_states, next_obs, reward, done, _ = vector_step(
            env, env_states, action, k_step)
        ep_ret = ep_ret + reward
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = (hist, valid, action, logp, value, reward, done)
        return (params, env_states, next_obs, hist, valid, done, rng,
                ep_ret, dsum, dcnt), out

    def attn_ppo_loss(params, batch):
        logits, value = module.apply(params, batch["hist"], batch["valid"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], -1)[:, 0]
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        return ppo_surrogate(logp, value, entropy, batch,
                             clip_param=config.clip_param,
                             vf_clip_param=config.vf_clip_param,
                             vf_loss_coeff=config.vf_loss_coeff,
                             entropy_coeff=config.entropy_coeff)

    def train_step(state: AttnAnakinState
                   ) -> Tuple[AttnAnakinState, Dict[str, jax.Array]]:
        carry = (state.params, state.env_states, state.obs, state.hist,
                 state.valid, state.prev_done, state.rng, state.ep_return,
                 state.done_return_sum, state.done_count)
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        (params, env_states, obs, hist, valid, prev_done, rng, ep_ret,
         dsum, dcnt) = carry
        hist_t, valid_t, act_t, logp_t, val_t, rew_t, done_t = traj

        nhist, nvalid = push(hist, valid, obs, prev_done)
        _, last_value = module.apply(params, nhist, nvalid)
        adv, vtarg = gae_jax(rew_t, val_t, done_t, last_value,
                             config.gamma, config.lambda_)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        # Feedforward training: every step's forward depends only on its
        # own window — flatten [T, N] and minibatch arbitrarily.
        flat = {
            "hist": hist_t.reshape((batch_total, K) + obs_dims),
            "valid": valid_t.reshape(batch_total, K),
            "actions": act_t.reshape(batch_total),
            "action_logp": logp_t.reshape(batch_total),
            "advantages": adv.reshape(batch_total),
            "value_targets": vtarg.reshape(batch_total),
        }

        from ray_tpu.rllib.algorithms.ppo import run_ppo_sgd

        (params, opt_state, rng), (losses, auxes) = run_ppo_sgd(
            params, state.opt_state, rng, attn_ppo_loss,
            lambda idx: {k_: v[idx] for k_, v in flat.items()},
            batch_total, mb_size, num_mb, config.num_sgd_iter, tx)

        new_state = AttnAnakinState(params, opt_state, env_states, obs,
                                    hist, valid, prev_done, rng, ep_ret,
                                    dsum, dcnt)
        metrics = {
            "total_loss": losses.mean(),
            "policy_loss": auxes["policy_loss"].mean(),
            "vf_loss": auxes["vf_loss"].mean(),
            "entropy": auxes["entropy"].mean(),
            "episode_return_sum": dsum,
            "episode_count": dcnt,
        }
        return new_state, metrics

    return module, init_fn, jax.jit(train_step), batch_total
