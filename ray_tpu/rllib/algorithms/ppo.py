"""PPO, two execution modes.

Reference: rllib/algorithms/ppo/ppo.py:350 (training_step: sample →
multi_gpu_train_one_step SGD → sync weights).  The TPU-first redesign:

- **anakin** (default): the Podracer/Anakin architecture (PAPERS.md) — env
  dynamics, rollout, GAE and the full minibatch-SGD epoch loop live inside
  ONE jitted train step; envs are a batched state pytree on device.  There
  is no sample transport at all: the [T, N] trajectory never leaves HBM.
  This is what makes ≥1M env-steps/s reachable — the reference's path
  (python envs → SampleBatch → GPU load) is bandwidth-bound at ~1e4/s/core.
- **actor**: reference-shaped path for envs that can't be jitted — CPU
  RolloutWorker actors sample fragments (with per-worker GAE like the
  reference's postprocessing), driver concatenates and the JaxLearner does
  the clipped-surrogate SGD on the mesh.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.evaluation.postprocessing import gae_jax
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)


def ppo_surrogate(logp, value, entropy, batch, *, clip_param, vf_clip_param,
                  vf_loss_coeff, entropy_coeff):
    """The clipped-surrogate objective from already-computed forward
    outputs — shared by the feedforward and recurrent paths."""
    ratio = jnp.exp(logp - batch["action_logp"])
    adv = batch["advantages"]
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
    vf_err = jnp.clip((value - batch["value_targets"]) ** 2,
                      0.0, vf_clip_param ** 2)
    policy_loss = -jnp.mean(surr)
    vf_loss = 0.5 * jnp.mean(vf_err)
    ent = jnp.mean(entropy)
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * ent
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": ent}


def ppo_loss(params, module, batch, *, clip_param, vf_clip_param,
             vf_loss_coeff, entropy_coeff):
    logp, value, entropy = module.forward_train(
        params, batch["obs"], batch["actions"])
    return ppo_surrogate(logp, value, entropy, batch,
                         clip_param=clip_param,
                         vf_clip_param=vf_clip_param,
                         vf_loss_coeff=vf_loss_coeff,
                         entropy_coeff=entropy_coeff)


def run_ppo_sgd(params, opt_state, rng, loss_fn, make_mb, total, mb_size,
                num_mb, num_sgd_iter, tx, sharded: bool = False,
                update_fn=None):
    """The shared permute→minibatch→update scaffolding for every PPO
    variant (feedforward, recurrent, attention): `make_mb(idx)` maps an
    index vector over `total` items (steps or env sequences) to a loss
    batch; `loss_fn(params, mb) -> (loss, aux)`.  One copy so fixes to
    the minibatch loop (e.g. the perm remainder drop) land everywhere.

    With `sharded=True` the caller runs inside a shard_map over the
    `data` mesh axis: `total`/`mb_size` are per-device, each device
    permutes its own shard, and the gradient (plus loss metrics) is
    pmean'd across the axis before the optimizer update — params stay
    replicated because every device applies the identical update.

    `update_fn(grads, opt_state, params) -> (params, opt_state)` swaps
    the reduce+apply half (the ZeRO / int8-collective plans from
    mesh.build_update_plan); it receives the RAW local grads and owns the
    cross-replica reduction.  None keeps the classic pmean + tx.update."""
    from ray_tpu.rllib.utils.mesh import pmean_if

    if update_fn is None:
        def update_fn(grads, opt_state, params):
            updates, opt_state = tx.update(pmean_if(grads, sharded),
                                           opt_state, params)
            return optax.apply_updates(params, updates), opt_state

    def sgd_epoch(carry, _):
        params, opt_state, rng = carry
        rng, k = jax.random.split(rng)
        perm = jax.random.permutation(k, total)

        def mb_step(carry, idx):
            params, opt_state = carry
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, make_mb(idx))
            loss = pmean_if(loss, sharded)
            aux = pmean_if(aux, sharded)
            params, opt_state = update_fn(grads, opt_state, params)
            return (params, opt_state), (loss, aux)

        idxs = perm[: num_mb * mb_size].reshape(num_mb, mb_size)
        (params, opt_state), (losses, auxes) = jax.lax.scan(
            mb_step, (params, opt_state), idxs)
        return (params, opt_state, rng), (losses.mean(),
                                          {k_: v.mean() for k_, v in
                                           auxes.items()})

    return jax.lax.scan(sgd_epoch, (params, opt_state, rng), None,
                        length=num_sgd_iter)


class AnakinState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array
    rng: jax.Array
    ep_return: jax.Array      # per-env running return
    done_return_sum: jax.Array
    done_count: jax.Array


def anakin_state_specs(opt_specs=None):
    """PartitionSpec prefix for AnakinState on the `data` mesh: params +
    optimizer replicated, env batch (states/obs/rng/returns) sharded on
    the axis, episode counters replicated (psum'd deltas).

    `opt_specs` overrides the optimizer subtree — the ZeRO plane passes
    `ZeroSharder.opt_specs` so each replica carries a 1/N state block."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.rllib.utils.mesh import DATA_AXIS

    return AnakinState(P(), opt_specs if opt_specs is not None else P(),
                       P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                       P(DATA_AXIS), P(), P())


def make_anakin_ppo(config: AlgorithmConfig):
    """Builds (init_fn, jitted train_step) for fully-on-device PPO.

    With ``config.num_devices`` set, the step is one SPMD program over a
    1-D ``data`` mesh (reference DP shape: one replica per GPU with grad
    all-reduce, rllib/core/rl_trainer/trainer_runner.py:75-90): each
    device rolls out N/D envs and runs the minibatch scan on its shard,
    with gradients/moments pmean'd across the axis — the only cross-chip
    traffic is the grad all-reduce riding ICI."""
    from ray_tpu.rllib.utils import mesh as mesh_util

    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    obs_shape = getattr(env, "obs_shape", None)
    spec = RLModuleSpec.for_env(env, tuple(config.hiddens))
    module = spec.build()

    N, T = config.num_envs, config.unroll_length
    batch_total = N * T
    mb_size = min(config.sgd_minibatch_size, batch_total)
    num_mb = batch_total // mb_size

    D, sharded, mesh = mesh_util.setup_data_mesh(config, N)
    if sharded:
        if mb_size % D:
            raise ValueError(f"sgd_minibatch_size={mb_size} not divisible "
                             f"by num_devices={D}")
        N_loc, mb_loc = N // D, mb_size // D
    else:
        N_loc, mb_loc = N, mb_size
    batch_loc = N_loc * T

    # The gradient-application plan (pmean / int8 collectives / ZeRO) —
    # shapes only, so the sharder is built before any init compiles.
    params_tmpl = jax.eval_shape(module.init, jax.random.PRNGKey(0),
                                 jnp.asarray(spec.example_obs()))
    update_fn, opt_init, opt_specs = mesh_util.build_update_plan(
        config, config.lr, config.grad_clip, params_tmpl, D, sharded)
    state_specs = anakin_state_specs(opt_specs)

    def _init(seed) -> AnakinState:
        rng = jax.random.PRNGKey(seed)
        rng, k_init, k_env = jax.random.split(rng, 3)
        env_states, obs = vector_reset(env, k_env, N)
        params = module.init(k_init, obs)
        return AnakinState(params, opt_init(params), env_states, obs,
                           mesh_util.split_rng(rng, D, sharded),
                           jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    if sharded:
        out_sh = mesh_util.state_sharding(mesh, state_specs)
        init_fn = jax.jit(_init, out_shardings=out_sh)
    else:
        init_fn = _init

    loss_fn = functools.partial(
        ppo_loss, clip_param=config.clip_param,
        vf_clip_param=config.vf_clip_param,
        vf_loss_coeff=config.vf_loss_coeff,
        entropy_coeff=config.entropy_coeff)

    def rollout_step(carry, _):
        params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
        rng, k_act, k_step = jax.random.split(rng, 3)
        action, logp, value = module.forward_exploration(params, obs, k_act)
        env_states, next_obs, reward, done, _ = vector_step(
            env, env_states, action, k_step)
        ep_ret = ep_ret + reward
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = (obs, action, logp, value, reward, done)
        return (params, env_states, next_obs, rng, ep_ret, dsum, dcnt), out

    def train_step(state: AnakinState) -> Tuple[AnakinState, Dict[str, jax.Array]]:
        # Inside shard_map every array is the per-device block: N_loc envs,
        # a [1, 2] rng row (unwrapped to this device's key), and the
        # replicated params/opt/counters.
        rng_in = mesh_util.unwrap_rng(state.rng, sharded)
        carry = (state.params, state.env_states, state.obs, rng_in,
                 state.ep_return, jnp.zeros(()), jnp.zeros(()))
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        params, env_states, obs, rng, ep_ret, dsum_d, dcnt_d = carry
        obs_t, act_t, logp_t, val_t, rew_t, done_t = traj  # [T, N_loc, ...]

        dsum = state.done_return_sum + mesh_util.psum_if(dsum_d, sharded)
        dcnt = state.done_count + mesh_util.psum_if(dcnt_d, sharded)

        _, last_value = module.apply(params, obs)
        adv, vtarg = gae_jax(rew_t, val_t, done_t, last_value,
                             config.gamma, config.lambda_)
        adv = mesh_util.normalize_global(adv, sharded)

        flat = {
            "obs": (obs_t.reshape(batch_loc, *obs_shape)
                    if obs_shape is not None
                    else obs_t.reshape(batch_loc, -1)),
            "actions": act_t.reshape(batch_loc),
            "action_logp": logp_t.reshape(batch_loc),
            "advantages": adv.reshape(batch_loc),
            "value_targets": vtarg.reshape(batch_loc),
        }

        (params, opt_state, rng), (losses, auxes) = run_ppo_sgd(
            params, state.opt_state, rng,
            lambda p, mb: loss_fn(p, module, mb),
            lambda idx: {k_: v[idx] for k_, v in flat.items()},
            batch_loc, mb_loc, num_mb, config.num_sgd_iter, None,
            sharded=sharded, update_fn=update_fn)

        new_state = AnakinState(params, opt_state, env_states, obs,
                                mesh_util.wrap_rng(rng, sharded),
                                ep_ret, dsum, dcnt)
        metrics = {
            "total_loss": losses.mean(),
            "policy_loss": auxes["policy_loss"].mean(),
            "vf_loss": auxes["vf_loss"].mean(),
            "entropy": auxes["entropy"].mean(),
            "episode_return_sum": dsum,
            "episode_count": dcnt,
        }
        return new_state, metrics

    # No donate_argnums: freshly-inited zero leaves (opt mu/nu, counters) can
    # share deduped buffers, which XLA rejects as double-donation.  The state
    # here is tiny; donation pays off in the LM train step, not this one.
    if sharded and config.zero_sharding != "off":
        step = mesh_util.zero_train_step(train_step, mesh, state_specs)
    elif sharded:
        step = mesh_util.shard_train_step(train_step, mesh, state_specs)
    else:
        step = jax.jit(train_step)
    return module, init_fn, step, batch_total


class PPO(Algorithm):
    _default_config_cls = PPOConfig
    _data_mesh_capable = True  # feedforward anakin only; guarded below

    # ---- anakin mode ----
    def _setup_anakin(self):
        if self.config.use_lstm and self.config.use_attention:
            raise ValueError("use_lstm and use_attention are exclusive")
        if self.config.use_lstm or self.config.use_attention:
            from ray_tpu.rllib.utils.mesh import reject_data_mesh

            reject_data_mesh(self.config, "recurrent/attention PPO")
        if self.config.use_lstm:
            from ray_tpu.rllib.algorithms.ppo_rnn import make_anakin_ppo_rnn

            (self.module, init_fn, self._train_step,
             self._steps_per_iter) = make_anakin_ppo_rnn(self.config)
        elif self.config.use_attention:
            from ray_tpu.rllib.algorithms.ppo_attn import make_anakin_ppo_attn

            (self.module, init_fn, self._train_step,
             self._steps_per_iter) = make_anakin_ppo_attn(self.config)
        else:
            (self.module, init_fn, self._train_step,
             self._steps_per_iter) = make_anakin_ppo(self.config)
        self._anakin_state = init_fn(self.config.seed)

    def evaluate(self, num_steps: int = 1000) -> Dict[str, Any]:
        """Extends the generic evaluator to the memory policies: the
        LSTM/attention modules need their carry/window threaded through
        the greedy rollout."""
        if self.config.mode == "anakin" and (self.config.use_lstm
                                             or self.config.use_attention):
            import jax

            from ray_tpu.rllib.env.jax_envs import make_jax_env

            if getattr(self, "_eval_rollout_fn", None) is None:
                env = make_jax_env(self.config.env) \
                    if isinstance(self.config.env, str) else self.config.env
                if self.config.use_lstm:
                    from ray_tpu.rllib.algorithms.ppo_rnn import \
                        make_rnn_eval_rollout

                    self._eval_rollout_fn = make_rnn_eval_rollout(
                        env, self.module, self.config.lstm_cell_size)
                else:
                    from ray_tpu.rllib.algorithms.ppo_attn import \
                        make_attn_eval_rollout

                    self._eval_rollout_fn = make_attn_eval_rollout(
                        env, self.module, self.config.attention_window)
                self._eval_rollout_key = jax.random.PRNGKey(
                    self.config.seed + 1)
            self._eval_rollout_key, k = jax.random.split(
                self._eval_rollout_key)
            r = self._eval_rollout_fn(self._anakin_state.params, k,
                                      num_steps)
            return {"episode_reward_mean": float(r)}
        return super().evaluate(num_steps)

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        # ONE host fetch for every metric: each separate device->host read
        # costs a full transfer round-trip (~0.1s on some backends), so
        # per-scalar float() here would dominate the whole train step.  The
        # previous counter values are remembered host-side from last iter.
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        prev_sum, prev_cnt = getattr(self, "_prev_counters", (0.0, 0.0))
        cum_sum = metrics.pop("episode_return_sum")
        cum_cnt = metrics.pop("episode_count")
        self._prev_counters = (cum_sum, cum_cnt)
        dsum, dcnt = cum_sum - prev_sum, cum_cnt - prev_cnt
        if dcnt > 0:
            self._ep_reward_ema = dsum / dcnt
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    # ---- actor mode ----
    def _setup_actor_mode(self):
        from ray_tpu.rllib.core.learner import JaxLearner
        from ray_tpu.rllib.evaluation.worker_set import WorkerSet
        from ray_tpu.rllib.env.py_envs import make_py_env

        if self.config.use_lstm or self.config.use_attention:
            # Silently training a memoryless MLP on a memory task is the
            # worst failure mode — refuse loudly instead.
            raise NotImplementedError(
                "use_lstm/use_attention policies run in anakin mode only; "
                "the actor-path sampling stack is feedforward")
        probe = make_py_env(self.config.env)
        # for_env is the one place pixel-vs-flat trunk selection lives:
        # pixel envs get the CNN trunk fed raw uint8 frames (the rollout
        # workers keep the dtype; NatureCNN does the /255).
        spec = RLModuleSpec.for_env(probe, tuple(self.config.hiddens))
        example = spec.example_obs()
        self.module = spec.build()
        if hasattr(probe, "close"):  # dimension probe only — release now
            probe.close()
        tx = optax.chain(optax.clip_by_global_norm(self.config.grad_clip or 1e9),
                         optax.adam(self.config.lr))
        self.learner = JaxLearner(
            self.module,
            functools.partial(ppo_loss,
                              clip_param=self.config.clip_param,
                              vf_clip_param=self.config.vf_clip_param,
                              vf_loss_coeff=self.config.vf_loss_coeff,
                              entropy_coeff=self.config.entropy_coeff),
            optimizer=tx, example_obs=example, seed=self.config.seed)
        self.workers = WorkerSet(self.config, spec)
        self._stream = None
        if self.config.sample_streaming:
            from ray_tpu.rllib.evaluation.sample_stream import SampleStream

            self._stream = SampleStream(
                self.workers, kind="gae",
                max_in_flight_per_worker=self.config.max_in_flight_per_worker,
                max_weight_staleness=self.config.max_weight_staleness)
            # Version 1 lands before the first fragment dispatch (FIFO
            # mailboxes), so no worker ever samples with params=None.
            self._stream.publish_weights(self.learner.get_weights())
        else:
            self.workers.sync_weights(self.learner.get_weights())

    def _run_ppo_epochs(self, train_batch) -> Dict[str, Any]:
        """The shared SGD half of both actor paths: advantage
        normalization + shuffled minibatch epochs on the learner."""
        adv = train_batch["advantages"]
        train_batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        metrics: Dict[str, Any] = {}
        for _ in range(self.config.num_sgd_iter):
            shuffled = train_batch.shuffle()
            for mb in shuffled.minibatches(
                    min(self.config.sgd_minibatch_size, len(shuffled))):
                metrics = self.learner.update(dict(mb))
        if metrics:
            from ray_tpu.rllib.core.learner import metrics_to_host

            metrics = metrics_to_host(metrics)
        return metrics

    def _training_step_actor(self) -> Dict[str, Any]:
        from ray_tpu.rllib.policy.sample_batch import SampleBatch

        if self._stream is None:
            # Legacy lockstep path (sample_streaming=False): barrier
            # sample -> train -> blocking weight sync.
            batches, ep_returns = self.workers.sample_sync()
            train_batch = SampleBatch.concat_samples(batches)
            metrics = self._run_ppo_epochs(train_batch)
            self.workers.sync_weights(self.learner.get_weights())
        else:
            # Streaming path: consume one fragment per worker slot as
            # they land — while the SGD epochs below run, every worker
            # still holds queued fragment work (the overlap the smoke
            # guards), and the new weights broadcast asynchronously.
            target = max(1, self.config.num_rollout_workers)
            batches, ep_returns = [], []
            for _ in range(target):
                frag = self._stream.next_fragment(timeout=120.0)
                if frag is None:
                    break
                batches.append(frag.batch)
                ep_returns.extend(frag.episode_returns)
            if not batches:
                raise ray_tpu.exceptions.RayTpuError(
                    "rollout stream produced no fragments within timeout")
            # Reuse last iteration's concat buffer (the learner consumed
            # it during the previous SGD epochs) — one batch-sized
            # allocation less per iteration.
            train_batch = SampleBatch.concat_samples_into(
                batches, getattr(self, "_train_buf", None))
            self._train_buf = train_batch
            metrics = self._run_ppo_epochs(train_batch)
            self._stream.publish_weights(self.learner.get_weights())
            st = self._stream.stats()
            metrics.update({
                "rollout_fragments_per_s": st["fragments_per_s"],
                "rollout_weight_lag_mean": st["weight_lag_mean"],
                "rollout_weight_lag_max": st["weight_lag_max"],
                "rollout_worker_idle_frac": st["worker_idle_frac"],
                "rollout_queue_depth": st["inflight"],
                "rollout_stale_dropped": st["stale_dropped"],
            })
        if ep_returns:
            self._ep_reward_ema = float(np.mean(ep_returns))
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        metrics["num_env_steps_sampled_this_iter"] = len(train_batch)
        return metrics
