"""MARWIL: monotonic advantage re-weighted imitation learning (offline).

Reference: rllib/algorithms/marwil/marwil.py (+ marwil_torch_policy.py):
exponentially advantage-weighted behavior cloning — policy loss
-E[exp(beta * A / c) * logp], advantages A = R - V(s) against a jointly
trained value head, c a running RMS normalizer of A (ma_adv_norm,
moving_average_sqd_adv_norm in the reference); beta=0 degenerates to BC
(which the reference implements as exactly this class).

Structure mirrors bc.py: the dataset loads once to device, discounted
MC returns are computed per episode at load time (numpy backward scan),
and each train() is one jitted minibatch-sweep step carrying the
advantage normalizer in the algorithm state.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.bc import BCConfig, make_greedy_eval_rollout
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.jax_envs import make_jax_env


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0                 # 0 => plain BC
        self.vf_coeff = 1.0
        self.ma_adv_norm_rate = 1e-2    # reference: moving_average update 1e-8*lr-ish; practical here
        self.marwil_minibatch_size = 256


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-episode discounted reward-to-go; the final (possibly truncated)
    episode treats end-of-data as terminal (reference:
    postprocessing.compute_advantages with use_gae=False)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out


class MARWILState(NamedTuple):
    params: Any
    opt_state: Any
    ma_adv_norm: jax.Array
    rng: jax.Array


class MARWIL(Algorithm):
    _default_config_cls = MARWILConfig

    def setup(self):
        from ray_tpu.rllib.offline import JsonReader

        config = self.config
        env = make_jax_env(config.env) if isinstance(config.env, str) \
            else config.env
        self._env = env
        spec = RLModuleSpec(obs_dim=env.obs_dim,
                            num_actions=env.num_actions,
                            hiddens=tuple(config.hiddens))
        self.module = spec.build()
        if config.offline_input is None:
            raise ValueError(
                "MARWIL requires config.offline_data(input_=path)")
        data = JsonReader(config.offline_input).read_all()
        obs = np.asarray(data["obs"], np.float32)
        actions = np.asarray(data["actions"], np.int32)
        rewards = np.asarray(data["rewards"], np.float32)
        dones = np.asarray(data.get("dones", np.zeros(len(rewards))),
                           np.float32)
        returns = discounted_returns(rewards, dones, config.gamma)
        self._obs = jnp.asarray(obs)
        self._actions = jnp.asarray(actions)
        self._returns = jnp.asarray(returns)
        n = self._obs.shape[0]
        mb = min(config.marwil_minibatch_size, n)

        tx_parts = []
        if config.grad_clip:
            tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
        tx_parts.append(optax.adam(config.lr))
        tx = optax.chain(*tx_parts)
        beta, vf_coeff = config.beta, config.vf_coeff
        rate = config.ma_adv_norm_rate
        obs_all, act_all, ret_all = self._obs, self._actions, self._returns

        def loss_fn(params, ma_adv_norm, obs, actions, returns):
            logp, value, _ent = self.module.forward_train(
                params, obs, actions)
            adv = returns - value
            vf_loss = jnp.mean(adv ** 2)
            adv_sg = jax.lax.stop_gradient(adv)
            new_norm = ma_adv_norm + rate * (
                jnp.mean(adv_sg ** 2) - ma_adv_norm)
            if beta != 0.0:
                # exp-weighted imitation, weights normalized by the running
                # RMS of the advantage and clipped for stability (the
                # reference clips the exponent at 20 implicitly via fp32;
                # we cap the weight explicitly).
                w = jnp.exp(jnp.clip(
                    beta * adv_sg / jnp.sqrt(jnp.maximum(new_norm, 1e-8)),
                    -10.0, 10.0))
            else:
                w = jnp.ones_like(adv_sg)
            policy_loss = -jnp.mean(w * logp)
            total = policy_loss + vf_coeff * vf_loss
            return total, (policy_loss, vf_loss, new_norm)

        def train_step(state: MARWILState):
            def one_update(carry, key):
                params, opt_state, ma = carry
                idx = jax.random.randint(key, (mb,), 0, n)
                (loss, (pl, vl, ma)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, ma, obs_all[idx],
                                           act_all[idx], ret_all[idx])
                updates, opt_state = tx.update(grads, opt_state)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, ma), (loss, pl, vl)

            rng, k = jax.random.split(state.rng)
            keys = jax.random.split(k, config.num_sgd_per_iter)
            (params, opt_state, ma), (losses, pls, vls) = jax.lax.scan(
                one_update, (state.params, state.opt_state,
                             state.ma_adv_norm), keys)
            return (MARWILState(params, opt_state, ma, rng),
                    losses.mean(), pls.mean(), vls.mean())

        rng = jax.random.PRNGKey(config.seed)
        rng, k_init = jax.random.split(rng)
        params = self.module.init(k_init, self._obs[:1])
        self._anakin_state = MARWILState(params, tx.init(params),
                                         jnp.ones(()), rng)
        self._train_step = jax.jit(train_step)

        self._eval_rollout = make_greedy_eval_rollout(env, self.module)
        self._eval_key = rng

    def train(self) -> Dict[str, Any]:
        import time

        t0 = time.perf_counter()
        (self._anakin_state, loss, pl, vl) = self._train_step(
            self._anakin_state)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "marwil_loss": float(loss),
                "policy_loss": float(pl),
                "vf_loss": float(vl),
                "ma_adv_norm": float(self._anakin_state.ma_adv_norm),
                "time_this_iter_s": time.perf_counter() - t0}

    def evaluate(self, num_steps: int = 1000) -> Dict[str, float]:
        self._eval_key, k = jax.random.split(self._eval_key)
        r = self._eval_rollout(self._anakin_state.params, k, num_steps)
        return {"episode_reward_mean": float(r)}
