"""DQN, anakin-style: the whole loop — env stepping, a device-resident
circular replay buffer, uniform sampling, double-Q updates, soft target
sync — lives inside ONE jitted train step.

Reference: rllib/algorithms/dqn/ (config surface: buffer, target network,
epsilon schedule, double_q, n_step=1 here) — but the architecture is the
TPU redesign: the reference's path (python envs → replay on CPU → GPU
load per batch) is replaced by a [capacity, ...] jax-array buffer updated
with dynamic_update_slice inside lax.scan, so transitions never leave HBM.
Soft target updates (polyak tau) replace the periodic hard copy: no
data-dependent control flow under jit.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step
from ray_tpu.models.mlp import MLP


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 1e-3
        # DQN-specific knobs (reference: DQNConfig.training(...))
        self.buffer_size = 50_000
        self.learning_starts = 1_000
        self.target_network_tau = 0.01
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 20_000
        self.double_q = True
        self.num_updates_per_iter = 8
        self.dqn_batch_size = 128


class QNetwork:
    """Q(s, ·) MLP head over the vector observation."""

    def __init__(self, obs_dim: int, num_actions: int, hiddens: Tuple[int, ...]):
        self.net = MLP(hiddens, num_actions, name="q_mlp")
        self.obs_dim = obs_dim
        self.num_actions = num_actions

    def init(self, key, obs):
        return self.net.init(key, obs)

    def apply(self, params, obs):
        return self.net.apply(params, obs)


class ReplayState(NamedTuple):
    obs: jax.Array        # [cap, obs_dim]
    actions: jax.Array    # [cap]
    rewards: jax.Array    # [cap]
    next_obs: jax.Array   # [cap, obs_dim]
    dones: jax.Array      # [cap]
    insert_pos: jax.Array  # scalar int
    size: jax.Array        # scalar int


class DQNState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array
    rng: jax.Array
    replay: ReplayState
    env_steps: jax.Array
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array


def make_replay_state(buffer_size: int, n_insert: int, obs_dim: int,
                      action_shape: Tuple[int, ...] = (),
                      action_dtype=jnp.int32) -> ReplayState:
    """Device replay buffer sized to a multiple of the per-iter insert so
    wrap inserts stay slice-aligned (dynamic_update_slice never clamps).
    Shared by the replay-family algorithms (DQN, SAC)."""
    cap = max(buffer_size, n_insert)
    cap = ((cap + n_insert - 1) // n_insert) * n_insert
    return ReplayState(
        obs=jnp.zeros((cap, obs_dim), jnp.float32),
        actions=jnp.zeros((cap,) + tuple(action_shape), action_dtype),
        rewards=jnp.zeros((cap,), jnp.float32),
        next_obs=jnp.zeros((cap, obs_dim), jnp.float32),
        dones=jnp.zeros((cap,), jnp.float32),
        insert_pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
    )


# The historical learner-owned HostReplay ring folded into the replay
# plane's local single-shard mode (PR 18): one replay implementation for
# DQN/SAC/TD3 actor modes, with the sharded object-plane mode one config
# knob away (replay_num_shards > 0).  run_actor_replay_iter re-exported
# here for back-compat with its historical import site.
from ray_tpu.rllib.execution.replay_plane import (  # noqa: E402,F401
    ReplayPlane,
    run_actor_replay_iter,
)


def make_offpolicy_rollout(env, act_fn):
    """Shared env-interaction scan body for the replay-family algorithms
    (SAC, TD3/DDPG): `act_fn(params, obs, key) -> action` is the only
    per-algorithm piece; the episode-return accounting (accumulate,
    fold into done-sums, reset on done) is the single copy all of them
    feed into Algorithm._episode_counter_metrics."""
    def rollout_step(carry, _):
        params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
        rng, k_act, k_step = jax.random.split(rng, 3)
        action = act_fn(params, obs, k_act)
        env_states, next_obs, reward, done, _ = vector_step(
            env, env_states, action, k_step)
        ep_ret = ep_ret + reward
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = {"obs": obs, "actions": action, "rewards": reward,
               "next_obs": next_obs, "dones": done.astype(jnp.float32)}
        return (params, env_states, next_obs, rng, ep_ret, dsum,
                dcnt), out

    return rollout_step


def _replay_insert(replay: ReplayState, batch: Dict[str, jax.Array]
                   ) -> ReplayState:
    """Insert [N] transitions at the circular cursor (N divides capacity)."""
    n = batch["actions"].shape[0]
    cap = replay.actions.shape[0]
    start = replay.insert_pos % cap

    def put(buf, vals):
        return jax.lax.dynamic_update_slice(
            buf, vals.astype(buf.dtype),
            (start,) + (0,) * (buf.ndim - 1))

    return ReplayState(
        obs=put(replay.obs, batch["obs"]),
        actions=put(replay.actions, batch["actions"]),
        rewards=put(replay.rewards, batch["rewards"]),
        next_obs=put(replay.next_obs, batch["next_obs"]),
        dones=put(replay.dones, batch["dones"]),
        insert_pos=(replay.insert_pos + n) % cap,
        size=jnp.minimum(replay.size + n, cap),
    )


def make_anakin_dqn(config: DQNConfig):
    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    net = QNetwork(env.obs_dim, env.num_actions, tuple(config.hiddens))
    tx_parts = []
    if config.grad_clip:
        tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
    tx_parts.append(optax.adam(config.lr))
    tx = optax.chain(*tx_parts)

    N, T = config.num_envs, config.unroll_length
    n_insert = N * T

    def init_fn(seed: int = 0) -> DQNState:
        rng = jax.random.PRNGKey(seed)
        rng, k_init, k_env = jax.random.split(rng, 3)
        env_states, obs = vector_reset(env, k_env, N)
        params = net.init(k_init, obs)
        replay = make_replay_state(config.buffer_size, n_insert, env.obs_dim)
        return DQNState(params, params, tx.init(params), env_states, obs,
                        rng, replay, jnp.zeros((), jnp.int32),
                        jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    def epsilon_at(step):
        # `step` ticks once per rollout scan step; each tick advances N
        # env steps, and epsilon_decay_steps is specified in env steps.
        frac = jnp.clip(step * N / config.epsilon_decay_steps, 0.0, 1.0)
        return (config.epsilon_initial
                + frac * (config.epsilon_final - config.epsilon_initial))

    def rollout_step(carry, _):
        params, env_states, obs, rng, step, ep_ret, dsum, dcnt = carry
        rng, k_eps, k_act, k_step = jax.random.split(rng, 4)
        q = net.apply(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        random_a = jax.random.randint(k_act, greedy.shape, 0,
                                      env.num_actions)
        eps = epsilon_at(step)
        explore = jax.random.uniform(k_eps, greedy.shape) < eps
        action = jnp.where(explore, random_a, greedy)
        env_states, next_obs, reward, done, _ = vector_step(
            env, env_states, action, k_step)
        ep_ret = ep_ret + reward
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = {"obs": obs, "actions": action, "rewards": reward,
               "next_obs": next_obs, "dones": done.astype(jnp.float32)}
        return (params, env_states, next_obs, rng, step + 1, ep_ret,
                dsum, dcnt), out

    def td_loss(params, target_params, batch):
        q = net.apply(params, batch["obs"])
        q_sa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
        q_next_target = net.apply(target_params, batch["next_obs"])
        if config.double_q:
            # Double-Q: online net picks the argmax, target net evaluates.
            q_next_online = net.apply(params, batch["next_obs"])
            next_a = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(q_next_target, next_a[:, None],
                                         1)[:, 0]
        else:
            q_next = jnp.max(q_next_target, axis=-1)
        target = batch["rewards"] + config.gamma * (1.0 - batch["dones"]) \
            * jax.lax.stop_gradient(q_next)
        td = q_sa - jax.lax.stop_gradient(target)
        return jnp.mean(optax.huber_loss(td)), jnp.mean(jnp.abs(td))

    def train_step(state: DQNState) -> Tuple[DQNState, Dict[str, jax.Array]]:
        carry = (state.params, state.env_states, state.obs, state.rng,
                 state.env_steps, state.ep_return, state.done_return_sum,
                 state.done_count)
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        (params, env_states, obs, rng, env_steps, ep_ret, dsum,
         dcnt) = carry
        flat = {k: v.reshape((N * T,) + v.shape[2:]) for k, v in traj.items()}
        replay = _replay_insert(state.replay, flat)

        def update(carry, key):
            params, target_params, opt_state = carry
            idx = jax.random.randint(key, (config.dqn_batch_size,), 0,
                                     jnp.maximum(replay.size, 1))
            batch = {
                "obs": replay.obs[idx],
                "actions": replay.actions[idx],
                "rewards": replay.rewards[idx],
                "next_obs": replay.next_obs[idx],
                "dones": replay.dones[idx],
            }
            (loss, td_abs), grads = jax.value_and_grad(
                td_loss, has_aux=True)(params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # Soft target sync (polyak) — the jit-friendly form of the
            # reference's periodic hard target copy.
            tau = config.target_network_tau
            target_params = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params)
            return (params, target_params, opt_state), (loss, td_abs)

        rng, k = jax.random.split(rng)
        keys = jax.random.split(k, config.num_updates_per_iter)
        warm = replay.size >= config.learning_starts
        (params, target_params, opt_state), (losses, tds) = jax.lax.scan(
            update, (state.params, state.target_params, state.opt_state),
            keys)
        # Before learning_starts: keep collecting, discard the updates.
        params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(warm, new, old), params, state.params)
        target_params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(warm, new, old), target_params,
            state.target_params)
        opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(warm, new, old), opt_state,
            state.opt_state)

        new_state = DQNState(params, target_params, opt_state, env_states,
                             obs, rng, replay, env_steps, ep_ret, dsum, dcnt)
        metrics = {
            "total_loss": losses.mean(),
            "td_error_abs": tds.mean(),
            "epsilon": epsilon_at(env_steps),
            "replay_size": replay.size,
            "episode_return_sum": dsum,
            "episode_count": dcnt,
        }
        return new_state, metrics

    return net, init_fn, jax.jit(train_step), N * T


class DQN(Algorithm):
    _default_config_cls = DQNConfig

    def _setup_anakin(self):
        (self.module, init_fn, self._train_step,
         self._steps_per_iter) = make_anakin_dqn(self.config)
        self._anakin_state = init_fn(self.config.seed)

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    # ---------------- actor mode (Ape-X shape) ----------------
    # CPU rollout actors collect raw transitions from non-jittable (gym)
    # envs into a learner-owned host replay buffer; the learner samples
    # minibatches and runs the SAME jitted TD update as the anakin path.
    # Reference: ApexDQN's replay-actor architecture + the learner-thread
    # consumer (rllib/execution/multi_gpu_learner_thread.py:20,187).
    def _setup_actor_mode(self):
        import cloudpickle

        from ray_tpu.rllib.env.py_envs import make_py_env
        from ray_tpu.rllib.evaluation.worker_set import (
            OffPolicyRolloutWorker,
            WorkerSet,
        )

        cfg = self.config
        probe = make_py_env(cfg.env)
        obs_dim, num_actions = probe.obs_dim, probe.num_actions
        net = QNetwork(obs_dim, num_actions, tuple(cfg.hiddens))
        self.module = net
        rng = jax.random.PRNGKey(cfg.seed)
        self._params = net.init(rng, jnp.zeros((1, obs_dim)))
        self._target_params = self._params
        tx_parts = []
        if cfg.grad_clip:
            tx_parts.append(optax.clip_by_global_norm(cfg.grad_clip))
        tx_parts.append(optax.adam(cfg.lr))
        self._tx = tx = optax.chain(*tx_parts)
        self._opt_state = tx.init(self._params)
        self._rng = rng
        self._env_steps = 0
        self._rb = ReplayPlane.from_config(cfg)
        self._host_rng = __import__("numpy").random.default_rng(cfg.seed)

        hiddens = tuple(cfg.hiddens)

        def act_factory():
            import jax as _jax
            import jax.numpy as _jnp

            from ray_tpu.rllib.algorithms.dqn import QNetwork as _QNet

            anet = _QNet(obs_dim, num_actions, hiddens)

            def act(params, obs, key, epsilon):
                q = anet.apply(params, obs)
                greedy = _jnp.argmax(q, axis=-1)
                k1, k2 = _jax.random.split(key)
                rand_a = _jax.random.randint(k1, greedy.shape, 0,
                                             num_actions)
                explore = _jax.random.uniform(k2, greedy.shape) < epsilon
                return _jnp.where(explore, rand_a, greedy)

            return act

        blob = cloudpickle.dumps(act_factory)

        def factory(i):
            return OffPolicyRolloutWorker.options(max_restarts=1).remote(
                cfg.env, blob, i, cfg.num_envs_per_worker,
                cfg.rollout_fragment_length, cfg.seed)

        self.workers = WorkerSet(cfg, None, worker_factory=factory)
        self.workers.sync_weights(jax.device_get(self._params))
        # Actor-mode update count: keep a replay ratio of ~4 gradient
        # samples per env step (the classic DQN regime: batch 32 every 4
        # steps).  num_updates_per_iter's default (8) is the anakin
        # path's; at actor-mode throughput (workers*envs*fragment steps
        # per iter) it under-trains — the CartPole gate plateaued at
        # ~98 with 8 updates/iter and clears 100 at the derived 16.
        steps_per_iter = (cfg.num_rollout_workers * cfg.num_envs_per_worker
                          * cfg.rollout_fragment_length)
        self._actor_updates = max(cfg.num_updates_per_iter,
                                  (4 * steps_per_iter) // cfg.dqn_batch_size)

        def td_loss(params, target_params, batch):
            q = net.apply(params, batch["obs"])
            q_sa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
            q_next_target = net.apply(target_params, batch["next_obs"])
            if cfg.double_q:
                q_next_online = net.apply(params, batch["next_obs"])
                next_a = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(q_next_target, next_a[:, None],
                                             1)[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) \
                * jax.lax.stop_gradient(q_next)
            td = q_sa - jax.lax.stop_gradient(target)
            return jnp.mean(optax.huber_loss(td)), jnp.mean(jnp.abs(td))

        def update_many(params, target_params, opt_state, batches):
            """lax.scan over [U, B, ...] stacked minibatches — one device
            round trip per training iteration."""
            def one(carry, batch):
                params, target_params, opt_state = carry
                (loss, td_abs), grads = jax.value_and_grad(
                    td_loss, has_aux=True)(params, target_params, batch)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                tau = cfg.target_network_tau
                target_params = jax.tree_util.tree_map(
                    lambda t, p: (1 - tau) * t + tau * p, target_params,
                    params)
                return (params, target_params, opt_state), (loss, td_abs)

            (params, target_params, opt_state), (losses, tds) = \
                jax.lax.scan(one, (params, target_params, opt_state),
                             batches)
            return params, target_params, opt_state, losses, tds

        self._update_many = jax.jit(update_many)

    def _epsilon_now(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _sync_params(self):
        return self._params

    def _training_step_actor(self):
        eps = self._epsilon_now()

        def do_updates(stacked, _keys):
            (self._params, self._target_params, self._opt_state, losses,
             tds) = self._update_many(self._params, self._target_params,
                                      self._opt_state, stacked)
            return {"total_loss": float(losses.mean()),
                    "td_error_abs": float(tds.mean())}

        metrics = run_actor_replay_iter(self, eps,
                                        self.config.dqn_batch_size,
                                        do_updates)
        metrics["epsilon"] = eps
        return metrics
