"""AlgorithmConfig: fluent builder (reference:
rllib/algorithms/algorithm_config.py — .environment/.rollouts/.training/
.resources/.framework chain, 2.9k LoC there; the essentials here)."""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = "CartPole-v1"
        self.env_config: Dict[str, Any] = {}
        # rollouts
        self.num_rollout_workers = 0
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.mode = "anakin"  # "anakin" (on-device envs) | "actor" (CPU actors)
        # streaming rollout plane (actor mode; see evaluation/sample_stream.py)
        self.sample_streaming = True          # PPO/IMPALA actor samplers
        self.max_in_flight_per_worker = 2     # fragment futures per worker
        # Consumption gate: fragments acted under weights older than this
        # many published versions are dropped before the learner sees
        # them.  None disables the gate.
        self.max_weight_staleness: Optional[int] = 4
        # Distributed replay plane (replay-family actor modes; see
        # rllib/execution/replay_plane.py).  0 shards = learner-local
        # single-shard mode (the historical HostReplay path); > 0 shards
        # stores fragments on the object plane behind shard actors.
        self.replay_num_shards = 0
        self.replay_prioritized = False   # priority-proportional sampling
        self.replay_alpha = 0.6           # priority exponent (when on)
        self.replay_beta = 0.4            # IS-weight exponent
        self.n_step = 1                   # n-step returns folded at insert
        self.replay_prefetch = 0          # gathered batches kept in flight
        # Staleness gate on SAMPLED rows (vs the rollout-plane gate below):
        # rows acted under weights older than this many versions get
        # importance weight 0.  None disables.
        self.replay_max_weight_staleness: Optional[int] = None
        # VectorEnv stepping: "serial" | "thread" | "subprocess" | "auto"
        # (auto: subprocess when the actor's host has >= 4 cores).
        self.env_parallelism = "serial"
        self.num_env_workers: Optional[int] = None  # per rollout actor
        # anakin-specific
        self.num_envs = 64
        self.unroll_length = 128
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_sgd_iter = 4
        self.sgd_minibatch_size = 512
        self.train_batch_size = 4000
        self.grad_clip: Optional[float] = 0.5
        # IMPALA
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.broadcast_interval = 1
        # model
        self.hiddens = (64, 64)
        self.use_lstm = False
        self.lstm_cell_size = 128
        # attention memory (reference model-config keys: use_attention,
        # attention_dim, attention_num_heads,
        # attention_num_transformer_units; window replaces the reference's
        # attention_memory_inference/training pair)
        self.use_attention = False
        self.attention_dim = 64
        self.attention_num_heads = 4
        self.attention_window = 8
        self.attention_num_layers = 1
        # resources / misc
        self.seed = 0
        self.framework_str = "jax"
        # Data-parallel learner mesh (reference: num_gpus on the learner,
        # rllib/core/rl_trainer/trainer_runner.py:75-90 — one DDP bucket
        # per GPU).  TPU-first redesign: the anakin train step shard_maps
        # over a `data` mesh axis — envs sharded, grads psum'd over ICI.
        # None = legacy single-device jit; an int (1 is valid) compiles
        # the SPMD program over that many devices.
        self.num_devices: Optional[int] = None
        # ZeRO-style update sharding over the data mesh (arxiv 2004.13336;
        # ray_tpu.parallel.zero): "off" replicates the optimizer state on
        # every device, "opt" shards it 1/N (grads still all-reduced),
        # "opt+grads" also reduce-scatters the gradients.  Requires
        # num_devices (the SPMD path).
        self.zero_sharding: str = "off"
        # Gradient-reduction wire format (EQuARX, arxiv 2506.17615;
        # ray_tpu.ops.collectives): "off" = fp32 psum, "int8" =
        # block-scaled int8 (~4x fewer bytes, loss-parity gated in
        # tests/test_zero.py).  Requires num_devices.
        self.quantized_collectives: str = "off"

    # ---- fluent sections ----
    def environment(self, env=None, env_config: Optional[dict] = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def rollouts(self, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 mode: Optional[str] = None,
                 sample_streaming: Optional[bool] = None,
                 max_in_flight_per_worker: Optional[int] = None,
                 max_weight_staleness: Optional[int] = None,
                 env_parallelism: Optional[str] = None,
                 num_env_workers: Optional[int] = None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
            if mode is None and num_rollout_workers > 0:
                self.mode = "actor"
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if mode is not None:
            self.mode = mode
        if sample_streaming is not None:
            self.sample_streaming = bool(sample_streaming)
        if max_in_flight_per_worker is not None:
            self.max_in_flight_per_worker = int(max_in_flight_per_worker)
        if max_weight_staleness is not None:
            self.max_weight_staleness = max_weight_staleness
        if env_parallelism is not None:
            if env_parallelism not in ("serial", "thread", "subprocess",
                                       "auto"):
                raise ValueError(
                    f"env_parallelism must be serial|thread|subprocess|"
                    f"auto, got {env_parallelism!r}")
            self.env_parallelism = env_parallelism
        if num_env_workers is not None:
            self.num_env_workers = int(num_env_workers)
        return self

    def env_runners(self, **kw):  # new-stack alias
        return self.rollouts(**kw)

    def anakin(self, num_envs: Optional[int] = None,
               unroll_length: Optional[int] = None):
        if num_envs is not None:
            self.num_envs = num_envs
        if unroll_length is not None:
            self.unroll_length = unroll_length
        self.mode = "anakin"
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if k == "model" and isinstance(v, dict):
                known = {"fcnet_hiddens", "use_lstm", "lstm_cell_size",
                         "use_attention", "attention_dim",
                         "attention_num_heads", "attention_window",
                         "attention_num_layers",
                         "attention_num_transformer_units"}
                unknown = set(v) - known
                if unknown:
                    # Same loudness as typo'd top-level params: a silent
                    # default fallback trains the wrong model.
                    raise ValueError(
                        f"unknown model config keys {sorted(unknown)}; "
                        f"known: {sorted(known)}")
                self.hiddens = tuple(v.get("fcnet_hiddens", self.hiddens))
                # Recurrent policy knobs (reference model config:
                # use_lstm / lstm_cell_size, catalog.py MODEL_DEFAULTS).
                self.use_lstm = bool(v.get("use_lstm", self.use_lstm))
                self.lstm_cell_size = int(v.get("lstm_cell_size",
                                                self.lstm_cell_size))
                # Attention-memory knobs (GTrXL path).
                self.use_attention = bool(v.get("use_attention",
                                                self.use_attention))
                self.attention_dim = int(v.get("attention_dim",
                                               self.attention_dim))
                self.attention_num_heads = int(
                    v.get("attention_num_heads", self.attention_num_heads))
                self.attention_window = int(
                    v.get("attention_window", self.attention_window))
                if ("attention_num_transformer_units" in v
                        and "attention_num_layers" in v):
                    raise ValueError(
                        "pass attention_num_transformer_units (reference "
                        "key) OR attention_num_layers, not both")
                self.attention_num_layers = int(
                    v.get("attention_num_transformer_units",
                          v.get("attention_num_layers",
                                self.attention_num_layers)))
                continue
            if not hasattr(self, k):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def framework(self, framework: str = "jax"):
        if framework != "jax":
            raise ValueError("this framework is jax-native; torch/tf ports "
                             "of user models belong in user space")
        return self

    def resources(self, num_devices: Optional[int] = None,
                  zero_sharding: Optional[str] = None,
                  quantized_collectives: Optional[str] = None, **kw):
        if num_devices is not None:
            self.num_devices = num_devices
        if zero_sharding is not None:
            if zero_sharding not in ("off", "opt", "opt+grads"):
                raise ValueError(f"zero_sharding must be off|opt|opt+grads, "
                                 f"got {zero_sharding!r}")
            self.zero_sharding = zero_sharding
        if quantized_collectives is not None:
            if quantized_collectives not in ("off", "int8"):
                raise ValueError(f"quantized_collectives must be off|int8, "
                                 f"got {quantized_collectives!r}")
            self.quantized_collectives = quantized_collectives
        return self

    def debugging(self, seed: Optional[int] = None, **kw):
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self, env=None):
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("no algorithm class bound to this config")
        return self.algo_class(self)
