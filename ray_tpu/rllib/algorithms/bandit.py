"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference: rllib/algorithms/bandit/ (BanditLinUCB / BanditLinTS over
DiscreteOnlineLinearRegression, bandit_torch_model.py) driven one
interaction per training_step.  TPU-first redesign: a training iteration
is ONE jitted lax.scan over `rounds_per_iter` interactions — the
per-arm (A, b) sufficient statistics, the Sherman-Morrison inverse
update, and the exploration rule all live on device; nothing but the
final metrics crosses to host.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class LinearBanditEnv:
    """Stationary linear contextual bandit: context x ~ N(0, I_d),
    E[reward | arm] = w_arm . x with N(0, noise) observation noise."""

    def __init__(self, num_arms: int = 5, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        self.num_arms, self.context_dim, self.noise = (num_arms,
                                                       context_dim, noise)
        self.weights = jax.random.normal(
            jax.random.PRNGKey(seed), (num_arms, context_dim)) / \
            jnp.sqrt(context_dim)

    def sample(self, rng):
        kx, kn = jax.random.split(rng)
        x = jax.random.normal(kx, (self.context_dim,))
        means = self.weights @ x
        noise = jax.random.normal(kn, (self.num_arms,)) * self.noise
        return x, means + noise, means


BANDIT_ENVS = {"LinearBandit-v0": LinearBanditEnv}


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BanditLinUCB)
        self.env = "LinearBandit-v0"
        self.rounds_per_iter = 256
        self.ucb_alpha = 1.0
        self.lin_ts_sigma = 0.3
        self.ridge_lambda = 1.0


class BanditState(NamedTuple):
    A_inv: jax.Array   # [K, d, d] inverse design matrices
    b: jax.Array       # [K, d]
    rng: jax.Array
    total_reward: jax.Array
    total_regret: jax.Array
    rounds: jax.Array


class BanditLinUCB(Algorithm):
    _default_config_cls = BanditConfig
    _explore = "ucb"

    def _setup_anakin(self):
        config = self.config
        env = (BANDIT_ENVS[config.env]() if isinstance(config.env, str)
               else config.env)
        K, d = env.num_arms, env.context_dim
        alpha = config.ucb_alpha
        ts_sigma = config.lin_ts_sigma
        explore = self._explore

        def choose(state, x, rng):
            theta = jnp.einsum("kij,kj->ki", state.A_inv, state.b)  # [K, d]
            mean = theta @ x
            if explore == "ucb":
                var = jnp.einsum("i,kij,j->k", x, state.A_inv, x)
                return jnp.argmax(mean + alpha * jnp.sqrt(var))
            # Linear Thompson: sample theta_k ~ N(theta, sigma^2 A_inv).
            eps = jax.random.normal(rng, (K, d))
            chol = jnp.linalg.cholesky(
                state.A_inv + 1e-6 * jnp.eye(d)[None])
            theta_s = theta + ts_sigma * jnp.einsum("kij,kj->ki", chol, eps)
            return jnp.argmax(theta_s @ x)

        def one_round(state: BanditState, _):
            rng, k_env, k_explore = jax.random.split(state.rng, 3)
            x, rewards, means = env.sample(k_env)
            arm = choose(state, x, k_explore)
            r = rewards[arm]
            regret = means.max() - means[arm]
            # Sherman–Morrison rank-1 update of this arm's A_inv.
            Ai = state.A_inv[arm]
            Aix = Ai @ x
            Ai_new = Ai - jnp.outer(Aix, Aix) / (1.0 + x @ Aix)
            state = BanditState(
                A_inv=state.A_inv.at[arm].set(Ai_new),
                b=state.b.at[arm].add(r * x),
                rng=rng,
                total_reward=state.total_reward + r,
                total_regret=state.total_regret + regret,
                rounds=state.rounds + 1)
            return state, (r, regret)

        def train_step(state: BanditState):
            state, (rs, regs) = jax.lax.scan(one_round, state, None,
                                             length=config.rounds_per_iter)
            metrics = {"episode_reward_mean": rs.mean(),
                       "regret_this_iter": regs.sum(),
                       "cumulative_regret": state.total_regret,
                       "rounds": state.rounds}
            return state, metrics

        lam = config.ridge_lambda
        self._anakin_state = BanditState(
            A_inv=jnp.tile(jnp.eye(d)[None] / lam, (K, 1, 1)),
            b=jnp.zeros((K, d)),
            rng=jax.random.PRNGKey(config.seed),
            total_reward=jnp.zeros(()),
            total_regret=jnp.zeros(()),
            rounds=jnp.zeros((), jnp.int32))
        self._train_step = jax.jit(train_step)
        self._steps_per_iter = config.rounds_per_iter

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics


class BanditLinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BanditLinTS


class BanditLinTS(BanditLinUCB):
    _default_config_cls = BanditLinTSConfig
    _explore = "ts"


class BanditLinUCBConfig(BanditConfig):
    pass
