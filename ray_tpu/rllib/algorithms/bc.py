"""BC (behavior cloning): supervised policy learning from offline data.

Reference: rllib/algorithms/bc/bc.py (BC = MARWIL with beta=0 — maximize
the policy log-likelihood of dataset actions; no env interaction during
training).  Here the dataset loads once into device memory and the whole
epoch — shuffle, minibatch sweep, SGD — is one jitted step; evaluation
runs the greedy policy in a jitted env rollout.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BC)
        self.lr = 1e-3
        self.offline_input = None     # path readable by JsonReader
        self.bc_minibatch_size = 256
        self.num_sgd_per_iter = 32

    def offline_data(self, input_=None):
        if input_ is not None:
            self.offline_input = input_
        return self


class BCState(NamedTuple):
    params: Any
    opt_state: Any
    rng: jax.Array


def make_greedy_eval_rollout(env, module, num_eval_envs: int = 16):
    """Jitted greedy in-env rollout returning mean completed-episode
    return — the offline algorithms' (BC, MARWIL) shared evaluator."""

    def eval_rollout(params, key, num_steps: int):
        k_env, k_run = jax.random.split(key)
        env_states, obs = vector_reset(env, k_env, num_eval_envs)

        def step(carry, _):
            env_states, obs, rng, ep_ret, dsum, dcnt = carry
            rng, k_s = jax.random.split(rng)
            action = module.forward_inference(params, obs)
            env_states, obs, reward, done, _ = vector_step(
                env, env_states, action, k_s)
            ep_ret = ep_ret + reward
            dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
            dcnt = dcnt + jnp.sum(done)
            ep_ret = jnp.where(done, 0.0, ep_ret)
            return (env_states, obs, rng, ep_ret, dsum, dcnt), None

        carry = (env_states, obs, k_run, jnp.zeros(num_eval_envs),
                 jnp.zeros(()), jnp.zeros(()))
        carry, _ = jax.lax.scan(step, carry, None, length=num_steps)
        _env_states, _obs, _rng, _ep, dsum, dcnt = carry
        return dsum / jnp.maximum(dcnt, 1.0)

    return jax.jit(eval_rollout, static_argnums=2)


class BC(Algorithm):
    _default_config_cls = BCConfig

    def setup(self):
        from ray_tpu.rllib.offline import JsonReader

        config = self.config
        env = make_jax_env(config.env) if isinstance(config.env, str) \
            else config.env
        self._env = env
        spec = RLModuleSpec(obs_dim=env.obs_dim,
                            num_actions=env.num_actions,
                            hiddens=tuple(config.hiddens))
        self.module = spec.build()
        if config.offline_input is None:
            raise ValueError("BC requires config.offline_data(input_=path)")
        data = JsonReader(config.offline_input).read_all()
        self._obs = jnp.asarray(np.asarray(data["obs"], np.float32))
        self._actions = jnp.asarray(np.asarray(data["actions"], np.int32))
        n = self._obs.shape[0]
        mb = min(config.bc_minibatch_size, n)

        tx_parts = []
        if config.grad_clip:
            tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
        tx_parts.append(optax.adam(config.lr))
        tx = optax.chain(*tx_parts)

        def loss_fn(params, obs, actions):
            logp, _value, _ent = self.module.forward_train(
                params, obs, actions)
            return -jnp.mean(logp)

        obs_all, act_all = self._obs, self._actions

        def train_step(state: BCState):
            def one_update(carry, key):
                params, opt_state = carry
                idx = jax.random.randint(key, (mb,), 0, n)
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, obs_all[idx], act_all[idx])
                updates, opt_state = tx.update(grads, opt_state)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            rng, k = jax.random.split(state.rng)
            keys = jax.random.split(k, config.num_sgd_per_iter)
            (params, opt_state), losses = jax.lax.scan(
                one_update, (state.params, state.opt_state), keys)
            return BCState(params, opt_state, rng), losses.mean()

        rng = jax.random.PRNGKey(config.seed)
        rng, k_init = jax.random.split(rng)
        params = self.module.init(k_init, self._obs[:1])
        self._anakin_state = BCState(params, tx.init(params), rng)
        self._train_step = jax.jit(train_step)

        self._eval_rollout = make_greedy_eval_rollout(env, self.module)
        self._eval_key = rng

    def train(self) -> Dict[str, Any]:
        import time

        t0 = time.perf_counter()
        self._anakin_state, loss = self._train_step(self._anakin_state)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "bc_loss": float(loss),
                "time_this_iter_s": time.perf_counter() - t0}

    def evaluate(self, num_steps: int = 1000) -> Dict[str, float]:
        self._eval_key, k = jax.random.split(self._eval_key)
        r = self._eval_rollout(self._anakin_state.params, k, num_steps)
        return {"episode_reward_mean": float(r)}
