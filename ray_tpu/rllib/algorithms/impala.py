"""IMPALA: async sampling + V-trace learner.

Reference: rllib/algorithms/impala/impala.py:534 (async sample requests →
learner queue → MultiGPULearnerThread with V-trace → periodic weight
broadcast).  Here the learner is the JaxLearner on the local mesh and the
async loop is driven with ray_tpu.wait over actor sample futures: as
fragments arrive they are V-trace-corrected and applied, and weights are
re-broadcast every `broadcast_interval` updates — same dataflow, no learner
thread needed because the update is a single device-side jit call.

An on-device "anakin" mode also exists: identical rollout to PPO's but with
the V-trace loss — on TPU the async/sync distinction dissolves when envs
live in the accelerator program.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.utils.vtrace import vtrace


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.num_sgd_iter = 1
        self.entropy_coeff = 0.01
        self.lr = 5e-4


def impala_loss(params, module, batch, *, gamma, clip_rho, clip_c,
                vf_loss_coeff, entropy_coeff):
    """batch tensors are time-major [T, N, ...] (V-trace needs time)."""
    T, N = batch["actions"].shape
    obs = batch["obs"].reshape(T * N, -1)
    actions = batch["actions"].reshape(T * N)
    logp, value, entropy = module.forward_train(params, obs, actions)
    logp = logp.reshape(T, N)
    value = value.reshape(T, N)
    vs, pg_adv = vtrace(batch["behaviour_logp"], logp, batch["rewards"],
                        jax.lax.stop_gradient(value), batch["dones"],
                        batch["last_value"], gamma, clip_rho, clip_c)
    policy_loss = -jnp.mean(logp * pg_adv)
    vf_loss = 0.5 * jnp.mean((value - vs) ** 2)
    ent = jnp.mean(entropy)
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * ent
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": ent}


class IMPALA(Algorithm):
    _default_config_cls = IMPALAConfig

    def _setup_actor_mode(self):
        from ray_tpu.rllib.core.learner import JaxLearner
        from ray_tpu.rllib.evaluation.worker_set import WorkerSet
        from ray_tpu.rllib.env.py_envs import make_py_env

        probe = make_py_env(self.config.env)
        spec = RLModuleSpec(obs_dim=probe.obs_dim,
                            num_actions=probe.num_actions,
                            hiddens=tuple(self.config.hiddens))
        self.module = spec.build()
        self._spec = spec
        example = np.zeros((1, probe.obs_dim), np.float32)
        tx = optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip or 1e9),
            optax.adam(self.config.lr))
        self.learner = JaxLearner(
            self.module,
            functools.partial(impala_loss, gamma=self.config.gamma,
                              clip_rho=self.config.vtrace_clip_rho,
                              clip_c=self.config.vtrace_clip_c,
                              vf_loss_coeff=self.config.vf_loss_coeff,
                              entropy_coeff=self.config.entropy_coeff),
            optimizer=tx, example_obs=example, seed=self.config.seed)
        self.workers = WorkerSet(self.config, spec)
        self.workers.sync_weights(self.learner.get_weights())
        self._inflight: Dict[Any, Any] = {}
        self._updates_since_broadcast = 0

    def _training_step_actor(self) -> Dict[str, Any]:
        import ray_tpu

        # Keep one sample request in flight per worker (async pipeline).
        for w in self.workers.workers:
            if not any(wk is w for wk, _ in self._inflight.items()):
                self._inflight[w] = w.sample_timemajor.remote()
        metrics: Dict[str, Any] = {}
        ep_returns = []
        target_updates = max(1, len(self.workers.workers))
        updates = 0
        while updates < target_updates:
            futs = list(self._inflight.values())
            ready, _ = ray_tpu.wait(futs, num_returns=1, timeout=120)
            if not ready:
                break
            fut = ready[0]
            worker = next(w for w, f in self._inflight.items() if f is fut)
            del self._inflight[worker]
            try:
                batch, eps = ray_tpu.get(fut)
            except ray_tpu.exceptions.RayTpuError:
                continue
            ep_returns.extend(eps)
            metrics = self.learner.update(batch)
            updates += 1
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= self.config.broadcast_interval:
                self.workers.sync_weights(self.learner.get_weights())
                self._updates_since_broadcast = 0
            self._inflight[worker] = worker.sample_timemajor.remote()
        if ep_returns:
            self._ep_reward_ema = float(np.mean(ep_returns))
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        metrics["num_env_steps_sampled_this_iter"] = (
            updates * self.config.rollout_fragment_length
            * self.config.num_envs_per_worker)
        return metrics
