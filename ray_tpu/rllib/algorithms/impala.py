"""IMPALA: async sampling + V-trace learner.

Reference: rllib/algorithms/impala/impala.py:534 (async sample requests →
learner queue → MultiGPULearnerThread with V-trace → periodic weight
broadcast).  Here the learner is the JaxLearner on the local mesh and the
async loop is driven with ray_tpu.wait over actor sample futures: as
fragments arrive they are V-trace-corrected and applied, and weights are
re-broadcast every `broadcast_interval` updates — same dataflow, no learner
thread needed because the update is a single device-side jit call.

An on-device "anakin" mode also exists: identical rollout to PPO's but with
the V-trace loss — on TPU the async/sync distinction dissolves when envs
live in the accelerator program.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.utils.vtrace import vtrace


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.num_sgd_iter = 1
        self.entropy_coeff = 0.01
        self.lr = 5e-4


def impala_loss(params, module, batch, *, gamma, clip_rho, clip_c,
                vf_loss_coeff, entropy_coeff):
    """batch tensors are time-major [T, N, ...] (V-trace needs time)."""
    T, N = batch["actions"].shape
    # Preserve trailing obs dims: pixel envs feed [T, N, H, W, C] to a
    # CNN trunk, flat envs [T, N, D] to the MLP.
    obs = batch["obs"].reshape((T * N,) + batch["obs"].shape[2:])
    actions = batch["actions"].reshape(T * N)
    logp, value, entropy = module.forward_train(params, obs, actions)
    logp = logp.reshape(T, N)
    value = value.reshape(T, N)
    vs, pg_adv = vtrace(batch["behaviour_logp"], logp, batch["rewards"],
                        jax.lax.stop_gradient(value), batch["dones"],
                        batch["last_value"], gamma, clip_rho, clip_c)
    policy_loss = -jnp.mean(logp * pg_adv)
    vf_loss = 0.5 * jnp.mean((value - vs) ** 2)
    ent = jnp.mean(entropy)
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * ent
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": ent}


class IMPALA(Algorithm):
    _default_config_cls = IMPALAConfig
    _data_mesh_capable = True  # anakin data mesh (APPO inherits)

    def _make_loss(self):
        """Loss-fn hook: APPO overrides this to swap in the clipped
        surrogate while reusing the whole IMPALA dataflow (anakin and
        actor modes both call it)."""
        c = self.config
        return functools.partial(impala_loss, gamma=c.gamma,
                                 clip_rho=c.vtrace_clip_rho,
                                 clip_c=c.vtrace_clip_c,
                                 vf_loss_coeff=c.vf_loss_coeff,
                                 entropy_coeff=c.entropy_coeff)

    # ---- anakin mode: on-device rollout + V-trace update in one jit ----
    def _setup_anakin(self):
        from ray_tpu.rllib.algorithms import ppo as ppo_mod
        from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step
        from ray_tpu.rllib.utils import mesh as mesh_util

        config = self.config
        env = make_jax_env(config.env) if isinstance(config.env, str) \
            else config.env
        spec = RLModuleSpec.for_env(env, tuple(config.hiddens))
        module = self.module = spec.build()
        N, T = config.num_envs, config.unroll_length
        loss_fn = self._make_loss()

        # Data-parallel mesh (same SPMD shape as PPO's: envs sharded on
        # the `data` axis, grads pmean'd — see ppo.make_anakin_ppo).
        D, sharded, mesh = mesh_util.setup_data_mesh(config, N)
        # Shared gradient-application plan: classic pmean, int8
        # collectives, or the ZeRO-sharded update — one recipe with PPO.
        params_tmpl = jax.eval_shape(module.init, jax.random.PRNGKey(0),
                                     jnp.asarray(spec.example_obs()))
        update_fn, opt_init, opt_specs = mesh_util.build_update_plan(
            config, config.lr, config.grad_clip or 1e9, params_tmpl, D,
            sharded)
        state_specs = ppo_mod.anakin_state_specs(opt_specs)

        def _init(seed):
            rng = jax.random.PRNGKey(seed)
            rng, k_init, k_env = jax.random.split(rng, 3)
            env_states, obs = vector_reset(env, k_env, N)
            params = module.init(k_init, obs)
            return ppo_mod.AnakinState(params, opt_init(params), env_states,
                                       obs, mesh_util.split_rng(rng, D, sharded),
                                       jnp.zeros(N), jnp.zeros(()),
                                       jnp.zeros(()))

        if sharded:
            init_fn = jax.jit(_init, out_shardings=mesh_util.state_sharding(
                mesh, state_specs))
        else:
            init_fn = _init

        def rollout_step(carry, _):
            params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            action, logp, _ = module.forward_exploration(params, obs, k_act)
            env_states, next_obs, reward, done, _ = vector_step(
                env, env_states, action, k_step)
            ep_ret = ep_ret + reward
            dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
            dcnt = dcnt + jnp.sum(done)
            ep_ret = jnp.where(done, 0.0, ep_ret)
            out = (obs, action, logp, reward, done)
            return (params, env_states, next_obs, rng, ep_ret, dsum, dcnt), out

        def train_step(state):
            rng_in = mesh_util.unwrap_rng(state.rng, sharded)
            carry = (state.params, state.env_states, state.obs, rng_in,
                     state.ep_return, jnp.zeros(()), jnp.zeros(()))
            carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
            params, env_states, obs, rng, ep_ret, dsum_d, dcnt_d = carry
            dsum = state.done_return_sum + mesh_util.psum_if(dsum_d, sharded)
            dcnt = state.done_count + mesh_util.psum_if(dcnt_d, sharded)
            obs_t, act_t, logp_t, rew_t, done_t = traj
            _, last_value = module.apply(params, obs)
            batch = {"obs": obs_t, "actions": act_t, "behaviour_logp": logp_t,
                     "rewards": rew_t, "dones": done_t.astype(jnp.float32),
                     "last_value": last_value}
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, module, batch)
            loss = mesh_util.pmean_if(loss, sharded)
            aux = mesh_util.pmean_if(aux, sharded)
            params, opt_state = update_fn(grads, state.opt_state, params)
            new_state = ppo_mod.AnakinState(
                params, opt_state, env_states, obs,
                mesh_util.wrap_rng(rng, sharded), ep_ret, dsum, dcnt)
            metrics = {"total_loss": loss, **aux,
                       "episode_return_sum": dsum, "episode_count": dcnt}
            return new_state, metrics

        self._anakin_state = init_fn(config.seed)
        if sharded and config.zero_sharding != "off":
            self._train_step = mesh_util.zero_train_step(
                train_step, mesh, state_specs)
        elif sharded:
            self._train_step = mesh_util.shard_train_step(
                train_step, mesh, state_specs)
        else:
            self._train_step = jax.jit(train_step)
        self._steps_per_iter = N * T

    def _training_step_anakin(self):
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        # One batched host fetch for all metrics (see ppo.py: per-scalar
        # float() pays a full transfer round-trip each).
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        prev_sum, prev_cnt = getattr(self, "_prev_counters", (0.0, 0.0))
        cum_sum = metrics.pop("episode_return_sum")
        cum_cnt = metrics.pop("episode_count")
        self._prev_counters = (cum_sum, cum_cnt)
        dsum, dcnt = cum_sum - prev_sum, cum_cnt - prev_cnt
        if dcnt > 0:
            self._ep_reward_ema = dsum / dcnt
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    def _setup_actor_mode(self):
        from ray_tpu.rllib.core.learner import JaxLearner
        from ray_tpu.rllib.evaluation.worker_set import WorkerSet
        from ray_tpu.rllib.env.py_envs import make_py_env

        probe = make_py_env(self.config.env)
        # Same pixel-vs-flat selection as the anakin path (for_env):
        # pixel gym envs ride the CNN trunk on raw uint8 frames.
        spec = RLModuleSpec.for_env(probe, tuple(self.config.hiddens))
        if hasattr(probe, "close"):  # dimension probe only — release now
            probe.close()
        self.module = spec.build()
        self._spec = spec
        example = spec.example_obs()
        tx = optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip or 1e9),
            optax.adam(self.config.lr))
        self.learner = JaxLearner(
            self.module, self._make_loss(),
            optimizer=tx, example_obs=example, seed=self.config.seed)
        self.workers = WorkerSet(self.config, spec)
        from ray_tpu.rllib.evaluation.sample_stream import SampleStream

        # The streaming rollout plane (sample_stream.py): K fragments in
        # flight per worker, versioned async weight broadcast, bounded
        # staleness — V-trace's behaviour/target correction absorbs the
        # staleness natively, so the gate here is a safety bound, not a
        # correctness requirement.
        self._stream = SampleStream(
            self.workers, kind="timemajor",
            max_in_flight_per_worker=self.config.max_in_flight_per_worker,
            max_weight_staleness=self.config.max_weight_staleness)
        self._stream.publish_weights(self.learner.get_weights())
        self._updates_since_broadcast = 0

    def _training_step_actor(self) -> Dict[str, Any]:
        metrics: Dict[str, Any] = {}
        ep_returns = []
        target_updates = max(1, len(self.workers.workers))
        updates = 0
        steps = 0
        while updates < target_updates:
            frag = self._stream.next_fragment(timeout=120.0)
            if frag is None:
                break
            ep_returns.extend(frag.episode_returns)
            metrics = self.learner.update(frag.batch)
            updates += 1
            steps += frag.env_steps
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= self.config.broadcast_interval:
                self._stream.publish_weights(self.learner.get_weights())
                self._updates_since_broadcast = 0
        if metrics:
            from ray_tpu.rllib.core.learner import metrics_to_host

            metrics = metrics_to_host(metrics)
        if ep_returns:
            self._ep_reward_ema = float(np.mean(ep_returns))
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        metrics["num_env_steps_sampled_this_iter"] = steps
        st = self._stream.stats()
        metrics.update({
            "rollout_fragments_per_s": st["fragments_per_s"],
            "rollout_weight_lag_mean": st["weight_lag_mean"],
            "rollout_weight_lag_max": st["weight_lag_max"],
            "rollout_worker_idle_frac": st["worker_idle_frac"],
            "rollout_stale_dropped": st["stale_dropped"],
        })
        return metrics
