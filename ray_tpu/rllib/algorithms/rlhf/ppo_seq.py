"""PPO on sequences: the RLHF learner step.

The objective is classic clipped-surrogate PPO (`ppo.ppo_surrogate`'s
math) applied to LM token sequences: each *sampled* token is one action,
its behavior logprob came from the serving engine's decode step (exact —
no recomputation drift), and the reward is terminal per sequence (a
scalar from the reward scorer).  With gamma=1 and a terminal reward the
Monte-Carlo return of every response position is the sequence reward, so

- ``value_targets[t] = R`` on response positions,
- ``advantages[t] = R - V_pre(s_t)`` (pre-update critic, the standard
  PPO bootstrap-free estimator), whitened over the masked positions,

both computed ONCE per batch inside the train step, followed by the
shared ``run_ppo_sgd`` permute->minibatch->epoch scaffolding — the same
scaffolding every PPO variant in this repo uses, with the
gradient-application recipe (plain adam / int8 collectives / ZeRO)
resolved by ``mesh.build_update_plan`` exactly as the anakin steps do.
The whole step (advantage pass + all SGD epochs) is ONE jit (one compile
per fixed ``[B, L]`` batch shape; the loop keeps shapes constant).
"""
from __future__ import annotations

import types
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.ppo import run_ppo_sgd
from ray_tpu.rllib.utils import mesh as mesh_util


def _masked_mean(x, mask):
    return (x * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def _masked_mean_global(x, mask, sharded):
    s = mesh_util.psum_if((x * mask).sum(), sharded)
    n = mesh_util.psum_if(mask.sum(), sharded)
    return s / jnp.maximum(n, 1.0)


def sequence_logprobs(logits, tokens):
    """``[B, L-1]`` log-softmax of ``tokens[:, 1:]`` under
    ``logits[:, :-1]`` — position t's logit row predicts token t+1."""
    lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    labels = tokens[:, 1:]
    return jnp.take_along_axis(lp, labels[..., None], axis=-1)[..., 0]


def sequence_ppo_loss(params, model, batch, *, clip_param, vf_coeff,
                      entropy_coeff):
    """Clipped-surrogate PPO over one minibatch of sequences.

    ``batch``: tokens [B, L] int32, response_mask [B, L] (1.0 on sampled
    tokens), behavior_logp [B, L], advantages [B, L], value_targets
    [B, L].  Mask/logp/adv/targets are indexed by the position of the
    sampled token; the value prediction for token t is the critic at
    t-1 (the state *before* emitting it)."""
    logits, values = model.apply({"params": params}, batch["tokens"])
    new_logp = sequence_logprobs(logits, batch["tokens"])  # [B, L-1]
    mask = batch["response_mask"][:, 1:]
    behavior = batch["behavior_logp"][:, 1:]
    adv = batch["advantages"][:, 1:]
    vt = batch["value_targets"][:, 1:]
    v_pred = values[:, :-1]

    ratio = jnp.exp(new_logp - behavior)
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
    policy_loss = -_masked_mean(surr, mask)
    vf_loss = 0.5 * _masked_mean((v_pred - vt) ** 2, mask)
    lp_full = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32),
                                 axis=-1)
    ent = _masked_mean(-(jnp.exp(lp_full) * lp_full).sum(-1), mask)
    # One-sample KL(behavior || current) estimate — drift telemetry.
    kl = _masked_mean(behavior - new_logp, mask)
    total = policy_loss + vf_coeff * vf_loss - entropy_coeff * ent
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": ent, "approx_kl": kl}


class SeqPPOLearner:
    """Jitted PPO-on-sequences learner for a ``GPT2WithValue`` module.

    ``update(batch_dict)`` runs advantage estimation plus
    ``num_sgd_iter`` shuffled-minibatch epochs in one compiled call and
    returns host metrics.  ``num_devices`` switches to the SPMD path
    (sequences sharded over the ``data`` mesh axis, params replicated)
    where ``zero_sharding``/``quantized_collectives`` select the PR 9
    gradient-application plans via ``mesh.build_update_plan``; without
    it both knobs fail loudly, exactly like the anakin steps."""

    def __init__(self, model, params, *, batch_size: int, pad_to: int,
                 lr: float = 1e-4, clip_param: float = 0.2,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 grad_clip: Optional[float] = 1.0, num_sgd_iter: int = 2,
                 minibatch_size: Optional[int] = None,
                 num_devices: Optional[int] = None,
                 zero_sharding: str = "off",
                 quantized_collectives: str = "off", seed: int = 0):
        self._model = model
        self.batch_size = int(batch_size)
        self.pad_to = int(pad_to)
        D, sharded, mesh = mesh_util.setup_data_mesh(
            types.SimpleNamespace(num_devices=num_devices),
            self.batch_size)
        mb = int(minibatch_size or self.batch_size)
        if mb > self.batch_size or self.batch_size % mb:
            raise ValueError(
                f"minibatch_size={mb} must divide batch_size="
                f"{self.batch_size}")
        if sharded and (self.batch_size % D or mb % D):
            raise ValueError(
                f"batch_size={self.batch_size} and minibatch_size={mb} "
                f"must be divisible by num_devices={D}")
        B_loc = self.batch_size // D if sharded else self.batch_size
        mb_loc = mb // D if sharded else mb
        num_mb = B_loc // mb_loc

        plan_cfg = types.SimpleNamespace(
            zero_sharding=zero_sharding,
            quantized_collectives=quantized_collectives)
        params_tmpl = jax.eval_shape(lambda: params)
        update_fn, opt_init, opt_specs = mesh_util.build_update_plan(
            plan_cfg, lr, grad_clip, params_tmpl, D, sharded)

        def loss_fn(p, mb_batch):
            return sequence_ppo_loss(
                p, model, mb_batch, clip_param=clip_param,
                vf_coeff=vf_coeff, entropy_coeff=entropy_coeff)

        def train_step(p, opt_state, rng, batch):
            # Advantages from the PRE-update critic, once per batch.
            _, values0 = model.apply({"params": p}, batch["tokens"])
            mask = batch["response_mask"]
            vt = batch["rewards"][:, None] * mask
            v_pre = jnp.concatenate(
                [jnp.zeros_like(values0[:, :1]), values0[:, :-1]], axis=1)
            adv_raw = (batch["rewards"][:, None] - v_pre) * mask
            m = _masked_mean_global(adv_raw, mask, sharded)
            var = _masked_mean_global((adv_raw - m) ** 2, mask, sharded)
            adv = (adv_raw - m) / (jnp.sqrt(var) + 1e-8) * mask
            flat = {"tokens": batch["tokens"], "response_mask": mask,
                    "behavior_logp": batch["behavior_logp"],
                    "advantages": adv, "value_targets": vt}
            (p, opt_state, rng), (losses, auxes) = run_ppo_sgd(
                p, opt_state, rng, loss_fn,
                lambda idx: {k: v[idx] for k, v in flat.items()},
                B_loc, mb_loc, num_mb, num_sgd_iter, None,
                sharded=sharded, update_fn=update_fn)
            metrics = {"total_loss": losses.mean()}
            metrics.update({k: v.mean() for k, v in auxes.items()})
            return p, opt_state, rng, metrics

        if sharded:
            from jax.sharding import PartitionSpec as P

            batch_specs = {"tokens": P(mesh_util.DATA_AXIS),
                           "response_mask": P(mesh_util.DATA_AXIS),
                           "behavior_logp": P(mesh_util.DATA_AXIS),
                           "rewards": P(mesh_util.DATA_AXIS)}
            mapped = mesh_util._shard_map(
                train_step, mesh=mesh,
                in_specs=(P(), opt_specs, P(), batch_specs),
                out_specs=(P(), opt_specs, P(), P()))
            self._step = jax.jit(mapped)
            init_sh = mesh_util.state_sharding(mesh, opt_specs)
            self._opt_state = jax.jit(
                opt_init, out_shardings=init_sh)(params)
        else:
            self._step = jax.jit(train_step)
            self._opt_state = opt_init(params)
        self._params = params
        self._rng = jax.random.PRNGKey(seed)
        self._sharded = sharded

    @property
    def params(self):
        return self._params

    @property
    def lm_params(self):
        """The policy subtree — exactly what ``LLMEngine.swap_weights``
        installs (the value head never ships to the serving plane)."""
        return self._params["lm"]

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        if batch["tokens"].shape != (self.batch_size, self.pad_to):
            raise ValueError(
                f"batch shape {batch['tokens'].shape} != compiled "
                f"({self.batch_size}, {self.pad_to}) — keep rollout batch "
                "shapes constant so the learner compiles once")
        step_batch = {k: batch[k] for k in
                      ("tokens", "response_mask", "behavior_logp",
                       "rewards")}
        self._params, self._opt_state, self._rng, metrics = self._step(
            self._params, self._opt_state, self._rng, step_batch)
        return {k: float(v) for k, v in jax.device_get(metrics).items()}
