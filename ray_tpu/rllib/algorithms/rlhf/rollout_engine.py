"""Process-separated rollout engine for the RLHF loop.

In-process, the generation engine and the learner share one XLA CPU
runtime, so a long SGD program starves the decode steps — the same
single-host contention the disaggregated-prefill bench documented (its
fix too): the real deployment shape gives each plane its own process.
:class:`EngineHost` is the actor body hosting one ``LLMEngine`` replica
(weights materialized seeded-identical from ``build_model``, the
serving-replica idiom — the learner starts from the same seed via
``GPT2WithValue.init_from_lm``), and :class:`RemoteEngine` is the
duck-typed driver-side client exposing exactly the surface
:class:`~ray_tpu.rllib.algorithms.rlhf.loop.RLHFLoop` uses
(``generate_rollouts`` / ``swap_weights`` / ``stats`` /
``recent_step_stamps`` / ``weight_version``), so the loop runs
unchanged against either.

The weight path is the versioned one-put broadcast: the loop ``put``s
the new lm params ONCE; the ref rides ``swap_weights.remote`` to every
engine replica (the task runtime materializes it actor-side — one
transfer per replica, one ``device_put`` per version inside the
engine).  Decode-step wall stamps compare across processes because
``time.monotonic`` is CLOCK_MONOTONIC, which is system-wide on Linux.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class EngineHost:
    """Actor body: one LLMEngine replica in its own process."""

    def __init__(self, model_kind: str = "gpt2",
                 config_kw: Optional[dict] = None, seed: int = 0,
                 **engine_kw):
        from ray_tpu.serve.llm_engine import LLMEngine, build_model

        model, params = build_model(model_kind, config_kw, seed)
        self.engine = LLMEngine(model, params, **engine_kw)

    def generate_rollouts(self, prompts, max_new_tokens: int = 16,
                          eos_id: Optional[int] = None,
                          sampling: Optional[list] = None
                          ) -> List[Dict[str, Any]]:
        return self.engine.generate_rollouts(prompts, max_new_tokens,
                                             eos_id, sampling=sampling)

    def swap_weights(self, params, version: int) -> int:
        return self.engine.swap_weights(params, version, timeout=120.0)

    def weight_version(self) -> int:
        return self.engine.weight_version

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def recent_step_stamps(self) -> List[float]:
        return self.engine.recent_step_stamps()

    def drain(self) -> bool:
        self.engine.close()
        return True


class RemoteEngine:
    """Driver-side client over an :class:`EngineHost` actor.

    ``max_concurrency`` on the actor lets ``swap_weights``/``stats``
    land while a ``generate_rollouts`` call is mid-decode — the hot
    swap must reach the engine loop *during* generation, not after."""

    def __init__(self, model_kind: str = "gpt2",
                 config_kw: Optional[dict] = None, seed: int = 0,
                 **engine_kw):
        import ray_tpu

        self._actor = ray_tpu.remote(EngineHost).options(
            max_concurrency=8).remote(model_kind, config_kw, seed,
                                      **engine_kw)
        self._ray = ray_tpu

    def generate_rollouts(self, prompts, max_new_tokens: int = 16,
                          eos_id: Optional[int] = None,
                          sampling: Optional[list] = None,
                          timeout: float = 600.0):
        return self._ray.get(
            self._actor.generate_rollouts.remote(
                prompts, max_new_tokens, eos_id, sampling),
            timeout=timeout)

    def swap_weights(self, params, version: int,
                     timeout: float = 120.0) -> int:
        return self._ray.get(
            self._actor.swap_weights.remote(params, version),
            timeout=timeout)

    @property
    def weight_version(self) -> int:
        return self._ray.get(self._actor.weight_version.remote(),
                             timeout=60.0)

    def stats(self) -> Dict[str, Any]:
        return self._ray.get(self._actor.stats.remote(), timeout=60.0)

    def recent_step_stamps(self) -> List[float]:
        return self._ray.get(self._actor.recent_step_stamps.remote(),
                             timeout=60.0)

    def close(self):
        try:
            self._ray.get(self._actor.drain.remote(), timeout=30.0)
        except Exception:
            pass
        try:
            self._ray.kill(self._actor)
        except Exception:
            pass
