"""Reward scoring for the RLHF loop, riding ``@serve.batch``.

A reward model is just another serving workload: scoring requests
arrive per rollout but want to execute batched.  ``RewardScorer``
wraps any ``(prompt_tokens, response_tokens) -> float`` function behind
the serve-plane batcher (``ray_tpu.serve.batching.batch``): concurrent
``score`` calls — the loop fans rollouts out over a small thread pool —
are auto-collected into one batched evaluation, exactly how a learned
reward model on a device wants to be fed.  Deploy the scorer under
``@serve.deployment`` for a remote replica set, or use it in-process.

Two toy preference rewards ship for the benchmarks: a target-token
reward (fraction of response tokens equal to a target — "positive
sentiment" reduced to its testable core) and a token-set variant.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Sequence

from ray_tpu.serve.batching import batch as serve_batch


def target_token_reward(target_token: int) -> Callable:
    """Reward = fraction of response tokens equal to ``target_token`` —
    a dense, noiseless preference signal: the optimal policy emits the
    target every step, so a learning curve on it is unambiguous."""
    t = int(target_token)

    def fn(prompt: Sequence[int], response: Sequence[int]) -> float:
        if not len(response):
            return 0.0
        return sum(1 for tok in response if int(tok) == t) / len(response)

    return fn


def token_set_reward(positive: Sequence[int]) -> Callable:
    """Reward = fraction of response tokens inside ``positive`` (the
    toy "positive sentiment" set)."""
    pos = {int(t) for t in positive}

    def fn(prompt: Sequence[int], response: Sequence[int]) -> float:
        if not len(response):
            return 0.0
        return sum(1 for tok in response if int(tok) in pos) / len(response)

    return fn


class RewardScorer:
    """Batched reward scorer (one ``@serve.batch`` entry point).

    ``score((prompt, response))`` blocks for one scalar; concurrent
    callers batch.  ``score_rollouts`` is the loop-facing helper: fan a
    rollout list over a thread pool (creating the concurrency the
    batcher collects), write each reward onto its rollout, return the
    list.  ``observed_batch_sizes`` proves batching happened."""

    def __init__(self, reward_fn: Callable, score_parallelism: int = 8):
        self._fn = reward_fn
        self._parallelism = max(1, int(score_parallelism))
        self.observed_batch_sizes: List[int] = []

    @serve_batch(max_batch_size=32, batch_wait_timeout_s=0.005)
    def score(self, items: List) -> List[float]:
        self.observed_batch_sizes.append(len(items))
        return [float(self._fn(p, r)) for p, r in items]

    def score_rollouts(self, rollouts) -> List[float]:
        if len(rollouts) == 1:
            rewards = [self.score((rollouts[0].prompt, rollouts[0].tokens))]
        else:
            with ThreadPoolExecutor(
                    max_workers=min(self._parallelism, len(rollouts)),
                    thread_name_prefix="rtpu-reward") as pool:
                rewards = list(pool.map(
                    lambda r: self.score((r.prompt, r.tokens)), rollouts))
        for r, rew in zip(rollouts, rewards):
            r.reward = float(rew)
        return rewards

    def close(self):
        """Release the underlying batcher's stage thread."""
        from ray_tpu.serve import batching

        batching.close_instance_batchers(self)
