"""RLHF: PPO fine-tuning of an LM whose rollouts run through the
serving engine (``ray_tpu.serve.llm_engine``).

The Podracer thesis at LLM scale on this repo's own planes: generation
rides the continuous-batching decode engine (behavior logprobs captured
per token, weight versions stamped per token), learning rides the
``run_ppo_sgd``/``build_update_plan`` training plane, and fresh weights
flow learner -> engine through ``LLMEngine.swap_weights`` — a
token-boundary hot swap off the versioned one-put broadcast.  See
``docs/RLHF.md``.
"""
from ray_tpu.rllib.algorithms.rlhf.ppo_seq import (  # noqa: F401
    SeqPPOLearner,
    sequence_ppo_loss,
)
from ray_tpu.rllib.algorithms.rlhf.reward import (  # noqa: F401
    RewardScorer,
    target_token_reward,
    token_set_reward,
)
from ray_tpu.rllib.algorithms.rlhf.loop import (  # noqa: F401
    RLHFConfig,
    RLHFLoop,
)
from ray_tpu.rllib.algorithms.rlhf.rollout_engine import (  # noqa: F401
    EngineHost,
    RemoteEngine,
)
