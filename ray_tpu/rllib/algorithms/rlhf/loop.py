"""RLHFLoop: train and serve in one cluster, generation never drains.

Topology (one arrow = one plane this repo already built):

    prompt dataset ──> flow.Stage (rollout producer, depth-bounded)
                           │  engine.generate_rollouts — continuous
                           │  batching amortizes the decode; every token
                           │  carries (behavior logprob, weight version)
                           ▼
    staleness gate (max_weight_staleness over version stamps)
                           ▼
    RewardScorer (@serve.batch)  ──>  SeqPPOLearner (run_ppo_sgd /
                           build_update_plan: adam | int8 | ZeRO)
                           ▼
    LLMEngine.swap_weights(ref, version)  — token-boundary hot swap off
    the versioned one-put broadcast (ray_tpu.put once, every replica
    resolves the same ref; one device_put per version, no recompile).

The perf thesis: the expensive half of RLHF is generation, and the
naive cycle (drain engine → generate → train → broadcast) idles each
plane in turn.  Here the rollout producer is a ``flow.Stage`` worker
thread, so while the learner runs SGD on batch *i* the engine is
already decoding batch *i+1* — the generation plane stays busy through
the SGD window (``gen_busy_frac_during_sgd`` in the step metrics, the
bench's >= 0.8 gate).  ``overlap=False`` degrades the stage to inline
execution: the exact drain-then-train baseline the bench compares
against.

Staleness: a hot swap lands mid-request by design, so rollouts can mix
versions.  Per-token behavior logprobs make the PPO ratio exact
regardless; the ``max_weight_staleness`` gate bounds how far *behind*
consumed experience may lag (the PR 5 rollout-plane rule), dropping —
never silently training on — older batches.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rllib.evaluation.sequence_batch import (
    SequenceBatch,
    SequenceRollout,
)


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b <<= 1
    return b


@dataclasses.dataclass
class RLHFConfig:
    """Knobs for :class:`RLHFLoop` (defaults are test-scale)."""

    rollouts_per_step: int = 8
    max_new_tokens: int = 16
    temperature: float = 1.0
    top_p: float = 1.0
    seed: int = 0
    # PPO
    lr: float = 1e-3
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: Optional[float] = 1.0
    num_sgd_iter: int = 2
    minibatch_size: Optional[int] = None
    # plane wiring
    max_weight_staleness: int = 2
    pipeline_depth: int = 1
    overlap: bool = True
    score_parallelism: int = 8
    pad_to: Optional[int] = None  # default: bucket(max prompt + max_new)
    # training-plane plans (mesh.build_update_plan)
    num_devices: Optional[int] = None
    zero_sharding: str = "off"
    quantized_collectives: str = "off"


class RLHFLoop:
    """One PPO iteration per ``step()``; generation overlaps SGD.

    ``engine`` is a started :class:`~ray_tpu.serve.llm_engine.LLMEngine`
    holding ``params["lm"]`` at version 0; ``model`` is the
    :class:`~ray_tpu.models.gpt2.GPT2WithValue` actor-critic whose
    ``lm`` subtree matches the engine's model; ``reward`` is a
    ``(prompt, response) -> float`` callable (wrapped in a
    :class:`RewardScorer`) or an existing scorer instance.
    """

    def __init__(self, engine, model, params, prompts: Sequence[Sequence[int]],
                 reward: Callable, config: Optional[RLHFConfig] = None):
        from ray_tpu.parallel import flow
        from ray_tpu.rllib.algorithms.rlhf.ppo_seq import SeqPPOLearner
        from ray_tpu.rllib.algorithms.rlhf.reward import RewardScorer

        self.config = c = config or RLHFConfig()
        self.engine = engine
        self._prompts = [list(map(int, p)) for p in prompts]
        if not self._prompts:
            raise ValueError("empty prompt dataset")
        max_len = max(len(p) for p in self._prompts) + c.max_new_tokens
        self.pad_to = int(c.pad_to or _bucket(max_len))
        if self.pad_to < max_len:
            raise ValueError(f"pad_to={self.pad_to} < longest possible "
                             f"sequence {max_len}")
        self.learner = SeqPPOLearner(
            model, params, batch_size=c.rollouts_per_step,
            pad_to=self.pad_to, lr=c.lr, clip_param=c.clip_param,
            vf_coeff=c.vf_coeff, entropy_coeff=c.entropy_coeff,
            grad_clip=c.grad_clip, num_sgd_iter=c.num_sgd_iter,
            minibatch_size=c.minibatch_size, num_devices=c.num_devices,
            zero_sharding=c.zero_sharding,
            quantized_collectives=c.quantized_collectives, seed=c.seed)
        self.scorer = reward if isinstance(reward, RewardScorer) \
            else RewardScorer(reward, c.score_parallelism)
        self._version = engine.weight_version
        self._seed_counter = 0
        self._prompt_cursor = 0
        self.stale_batches_dropped = 0
        self.steps_done = 0
        # The rollout producer: workers=1 generates batch i+1 on a
        # background thread while step() trains on batch i (the
        # overlap); workers=0 is the inline drain-then-train baseline.
        self._gen = flow.Stage(
            self._batch_source(), self._generate,
            depth=max(1, int(c.pipeline_depth)),
            workers=1 if c.overlap else 0,
            name="rlhf_rollout", export_metrics=False)

    # ---- rollout production (stage worker thread) --------------------
    def _batch_source(self):
        from ray_tpu.serve.sampling import SamplingParams

        c = self.config
        while True:
            batch = []
            for _ in range(c.rollouts_per_step):
                prompt = self._prompts[self._prompt_cursor
                                       % len(self._prompts)]
                self._prompt_cursor += 1
                samp = SamplingParams(
                    temperature=c.temperature, top_p=c.top_p,
                    seed=c.seed * 1_000_003 + self._seed_counter)
                self._seed_counter += 1
                batch.append((prompt, samp))
            yield batch

    def _generate(self, batch) -> Dict[str, Any]:
        t0 = time.monotonic()
        prompts = [p for p, _ in batch]
        sampling = [s for _, s in batch]
        recs = self.engine.generate_rollouts(
            prompts, self.config.max_new_tokens, sampling=sampling)
        rollouts = [SequenceRollout.from_engine(r) for r in recs]
        return {"rollouts": rollouts, "gen_start": t0,
                "gen_end": time.monotonic()}

    # ---- one PPO iteration (caller thread) ---------------------------
    def step(self) -> Dict[str, Any]:
        c = self.config
        while True:
            item = next(self._gen)
            rollouts: List[SequenceRollout] = item["rollouts"]
            # Batch-granular staleness gate: the batch was generated as
            # one window, so it is consumable iff its oldest token is
            # fresh enough (keeps the learner's [B, L] shape constant).
            oldest = min(r.min_version for r in rollouts)
            if self._version - oldest <= c.max_weight_staleness:
                break
            self.stale_batches_dropped += 1
        rewards = self.scorer.score_rollouts(rollouts)
        batch = SequenceBatch.from_rollouts(rollouts, self.pad_to)
        sgd_t0 = time.monotonic()
        work0 = self.engine.stats()["work_seconds"]
        metrics = self.learner.update(batch.as_dict())
        sgd_t1 = time.monotonic()
        work1 = self.engine.stats()["work_seconds"]

        # Versioned one-put broadcast: put once, every engine replica
        # resolves the same ref (in-process engines take the tree).
        self._version += 1
        lm = self.learner.lm_params
        payload = lm
        try:
            import ray_tpu

            if ray_tpu.is_initialized():
                import jax

                payload = ray_tpu.put(jax.device_get(lm))
        except Exception:
            payload = lm
        swap_t0 = time.monotonic()
        self.engine.swap_weights(payload, self._version, timeout=120.0)
        swap_s = time.monotonic() - swap_t0

        self.steps_done += 1
        metrics.update({
            "reward_mean": float(np.mean(rewards)),
            "reward_max": float(np.max(rewards)),
            "weight_version": self._version,
            "stale_batches_dropped": self.stale_batches_dropped,
            "gen_window": (item["gen_start"], item["gen_end"]),
            "sgd_window": (sgd_t0, sgd_t1),
            "sgd_seconds": sgd_t1 - sgd_t0,
            "swap_seconds": swap_s,
            "gen_busy_frac_during_sgd": (
                (work1 - work0) / max(sgd_t1 - sgd_t0, 1e-9)),
            "response_tokens": batch.num_response_tokens,
        })
        return metrics

    def run(self, num_steps: int) -> List[Dict[str, Any]]:
        return [self.step() for _ in range(num_steps)]

    @property
    def weight_version(self) -> int:
        return self._version

    def close(self):
        self._gen.close()
        try:
            self.scorer.close()
        except Exception:
            pass
