"""APPO: asynchronous PPO — IMPALA's dataflow with PPO's clipped
surrogate on V-trace-corrected advantages.

Reference: rllib/algorithms/appo/appo.py (APPO = IMPALA subclass with
use_critic/use_kl_loss/clip_param config surface; loss
appo_torch_policy.py — importance ratios against the behaviour policy,
V-trace returns as the critic target, PPO clipping on the policy term).
Here the whole thing is the IMPALA class with one swapped loss: the
anakin mode runs the env + V-trace + clipped update in a single jitted
step, the actor mode feeds async CPU rollouts through the same loss on
the learner mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.utils.vtrace import vtrace


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2
        self.lr = 5e-4
        self.num_sgd_iter = 1


def appo_loss(params, module, batch, *, gamma, clip_rho, clip_c,
              vf_loss_coeff, entropy_coeff, clip_param):
    """Time-major [T, N, ...] batch like impala_loss; the policy term is
    PPO's clipped surrogate with the importance ratio taken against the
    behaviour policy and the advantage from V-trace."""
    T, N = batch["actions"].shape
    # Preserve trailing obs dims (pixel envs feed the CNN trunk).
    obs = batch["obs"].reshape((T * N,) + batch["obs"].shape[2:])
    actions = batch["actions"].reshape(T * N)
    logp, value, entropy = module.forward_train(params, obs, actions)
    logp = logp.reshape(T, N)
    value = value.reshape(T, N)
    vs, pg_adv = vtrace(batch["behaviour_logp"], logp, batch["rewards"],
                        jax.lax.stop_gradient(value), batch["dones"],
                        batch["last_value"], gamma, clip_rho, clip_c)
    adv = jax.lax.stop_gradient(pg_adv)
    ratio = jnp.exp(logp - batch["behaviour_logp"])
    policy_loss = -jnp.mean(jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv))
    vf_loss = 0.5 * jnp.mean((value - vs) ** 2)
    ent = jnp.mean(entropy)
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * ent
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": ent}


class APPO(IMPALA):
    _default_config_cls = APPOConfig

    def _make_loss(self):
        c = self.config
        return functools.partial(appo_loss, gamma=c.gamma,
                                 clip_rho=c.vtrace_clip_rho,
                                 clip_c=c.vtrace_clip_c,
                                 vf_loss_coeff=c.vf_loss_coeff,
                                 entropy_coeff=c.entropy_coeff,
                                 clip_param=c.clip_param)
