"""DynaQ: model-based DQN (the Dyna architecture).

Reference family: rllib's model-based algorithms (MBMPO,
rllib/algorithms/mbmpo/ — learn an ensemble dynamics model from real
transitions, train the policy on imagined rollouts).  This representative
keeps the family's defining loop — real experience trains a DYNAMICS
MODEL, the model manufactures imagined transitions, and the value
learner consumes both — in the anakin shape: env rollout, replay,
model fit, imagination, and the double-Q update are all one jitted
train step.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.dqn import (
    DQNConfig,
    QNetwork,
    ReplayState,
    _replay_insert,
    make_replay_state,
)
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset, vector_step
from ray_tpu.models.mlp import MLP


class DynaQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DynaQ
        # Model-based knobs: imagined minibatches per real update and
        # the dynamics-model learning rate.
        self.model_lr = 1e-3
        self.imagined_ratio = 1.0   # imagined batch size / real batch size
        self.model_updates_per_iter = 4


class DynaState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    model_params: Any
    model_opt: Any
    env_states: Any
    obs: jax.Array
    rng: jax.Array
    replay: ReplayState
    env_steps: jax.Array
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array
    model_loss: jax.Array


class DynaQ(Algorithm):
    _default_config_cls = DynaQConfig

    def _setup_anakin(self):
        config = self.config
        env = make_jax_env(config.env) if isinstance(config.env, str) \
            else config.env
        N, T = config.num_envs, config.unroll_length
        obs_dim = env.obs_dim
        A = env.num_actions
        qnet = QNetwork(obs_dim, A, tuple(config.hiddens))
        # Dynamics model: (obs, onehot action) -> (delta obs, reward,
        # done logit).
        model = MLP(features=tuple(config.hiddens),
                    out_dim=obs_dim + 2)
        gamma = config.gamma
        B = config.dqn_batch_size
        BI = int(B * config.imagined_ratio)
        tx = optax.adam(config.lr)
        mtx = optax.adam(config.model_lr)

        def model_in(obs, act):
            return jnp.concatenate(
                [obs, jax.nn.one_hot(act, A)], axis=-1)

        def model_pred(mp, obs, act):
            out = model.apply(mp, model_in(obs, act))
            next_obs = obs + out[..., :obs_dim]
            reward = out[..., obs_dim]
            done_logit = out[..., obs_dim + 1]
            return next_obs, reward, done_logit

        def model_loss_fn(mp, batch):
            next_pred, r_pred, d_logit = model_pred(
                mp, batch["obs"], batch["actions"])
            l_obs = jnp.mean((next_pred - batch["next_obs"]) ** 2)
            l_r = jnp.mean((r_pred - batch["rewards"]) ** 2)
            l_d = jnp.mean(optax.sigmoid_binary_cross_entropy(
                d_logit, batch["dones"]))
            return l_obs + l_r + l_d

        def q_loss_fn(p, tp, batch):
            q = qnet.apply(p, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), 1)[:, 0]
            nq_online = qnet.apply(p, batch["next_obs"])
            nq_target = qnet.apply(tp, batch["next_obs"])
            # Double-Q: online argmax, target evaluation.
            na = jnp.argmax(nq_online, axis=-1)
            nv = jnp.take_along_axis(nq_target, na[:, None], 1)[:, 0]
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * nv
            return jnp.mean((q_sel - jax.lax.stop_gradient(target)) ** 2)

        def sample_real(replay, rng, n):
            idx = jax.random.randint(rng, (n,), 0,
                                     jnp.maximum(replay.size, 1))
            return {k: getattr(replay, k)[idx]
                    for k in ("obs", "actions", "rewards", "next_obs",
                              "dones")}

        def imagine(mp, p, replay, rng, n):
            """Dyna imagination: start from REAL replayed states, act
            epsilon-greedily with the CURRENT policy, step the MODEL."""
            k_idx, k_eps, k_act = jax.random.split(rng, 3)
            idx = jax.random.randint(k_idx, (n,), 0,
                                     jnp.maximum(replay.size, 1))
            obs = replay.obs[idx]
            greedy = jnp.argmax(qnet.apply(p, obs), axis=-1)
            rand = jax.random.randint(k_act, (n,), 0, A)
            act = jnp.where(jax.random.uniform(k_eps, (n,)) < 0.1,
                            rand, greedy)
            next_obs, reward, done_logit = model_pred(mp, obs, act)
            return {"obs": obs, "actions": act, "rewards": reward,
                    "next_obs": jax.lax.stop_gradient(next_obs),
                    "dones": (jax.nn.sigmoid(done_logit) > 0.5
                              ).astype(jnp.float32)}

        def rollout(state, rng):
            def one(carry, _):
                env_states, obs, rng, ep_ret, dsum, dcnt, steps, p = carry
                rng, k_eps, k_rand, k_step = jax.random.split(rng, 4)
                eps = jnp.clip(
                    1.0 - (1.0 - config.epsilon_final) * steps
                    / config.epsilon_decay_steps,
                    config.epsilon_final, 1.0)
                greedy = jnp.argmax(qnet.apply(p, obs), axis=-1)
                rand = jax.random.randint(k_rand, (N,), 0, A)
                act = jnp.where(
                    jax.random.uniform(k_eps, (N,)) < eps, rand, greedy)
                env_states, next_obs, r, done, _ = vector_step(
                    env, env_states, act, k_step)
                ep_ret = ep_ret + r
                dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
                dcnt = dcnt + jnp.sum(done)
                ep_ret = jnp.where(done, 0.0, ep_ret)
                out = (obs, act, r, next_obs, done.astype(jnp.float32))
                return (env_states, next_obs, rng, ep_ret, dsum, dcnt,
                        steps + N, p), out

            carry = (state.env_states, state.obs, rng, state.ep_return,
                     state.done_return_sum, state.done_count,
                     state.env_steps, state.params)
            carry, tr = jax.lax.scan(one, carry, None, length=T)
            env_states, obs, _, ep_ret, dsum, dcnt, steps, _ = carry
            o, a, r, no, d = tr
            flat = {"obs": o.reshape(N * T, obs_dim),
                    "actions": a.reshape(N * T),
                    "rewards": r.reshape(N * T),
                    "next_obs": no.reshape(N * T, obs_dim),
                    "dones": d.reshape(N * T)}
            return env_states, obs, ep_ret, dsum, dcnt, steps, flat

        def train_step(state: DynaState):
            rng, k_roll, k_model, k_q = jax.random.split(state.rng, 4)
            (env_states, obs, ep_ret, dsum, dcnt, steps,
             flat) = rollout(state, k_roll)
            replay = _replay_insert(state.replay, flat)

            # 1) Fit the dynamics model on real replayed transitions.
            def model_update(carry, k):
                mp, mopt = carry
                batch = sample_real(replay, k, B)
                loss, grads = jax.value_and_grad(model_loss_fn)(mp, batch)
                up, mopt = mtx.update(grads, mopt, mp)
                return (optax.apply_updates(mp, up), mopt), loss

            (mp, mopt), mlosses = jax.lax.scan(
                model_update, (state.model_params, state.model_opt),
                jax.random.split(k_model, config.model_updates_per_iter))

            # 2) Q updates on real + imagined transitions.
            def q_update(carry, k):
                p, tp, opt = carry
                k_real, k_imag = jax.random.split(k)
                real = sample_real(replay, k_real, B)
                imag = imagine(mp, p, replay, k_imag, BI)
                batch = {kk: jnp.concatenate([real[kk], imag[kk]])
                         for kk in real}
                loss, grads = jax.value_and_grad(q_loss_fn)(p, tp, batch)
                up, opt = tx.update(grads, opt, p)
                p = optax.apply_updates(p, up)
                tp = jax.tree.map(
                    lambda t, o: t * (1 - config.target_network_tau)
                    + o * config.target_network_tau, tp, p)
                return (p, tp, opt), loss

            warm = replay.size >= config.learning_starts
            (p, tp, opt), qlosses = jax.lax.scan(
                q_update, (state.params, state.target_params,
                           state.opt_state),
                jax.random.split(k_q, config.num_updates_per_iter))
            p, tp, opt = jax.tree.map(
                lambda new, old: jnp.where(warm, new, old),
                (p, tp, opt),
                (state.params, state.target_params, state.opt_state))

            new_state = DynaState(p, tp, opt, mp, mopt, env_states, obs,
                                  rng, replay, steps, ep_ret, dsum, dcnt,
                                  mlosses.mean())
            metrics = {"total_loss": qlosses.mean(),
                       "model_loss": mlosses.mean(),
                       "episode_return_sum": dsum,
                       "episode_count": dcnt}
            return new_state, metrics

        key = jax.random.PRNGKey(config.seed)
        k_q, k_m, k_env, k_rng = jax.random.split(key, 4)
        env_states, obs0 = vector_reset(env, k_env, N)
        qp = qnet.init(k_q, obs0)
        mp = model.init(k_m, model_in(obs0, jnp.zeros(N, jnp.int32)))
        self._anakin_state = DynaState(
            qp, qp, tx.init(qp), mp, mtx.init(mp), env_states, obs0,
            k_rng, make_replay_state(config.buffer_size, N * T, obs_dim),
            jnp.zeros((), jnp.int32), jnp.zeros(N), jnp.zeros(()),
            jnp.zeros(()), jnp.zeros(()))
        self._train_step = jax.jit(train_step)
        self._steps_per_iter = N * T
        self.module = qnet

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics
