"""SAC (soft actor-critic), anakin-style: continuous control with the
whole loop — env stepping, HBM replay buffer, twin-Q updates, squashed-
Gaussian policy, automatic entropy temperature — inside ONE jitted step.

Reference: rllib/algorithms/sac/ (config surface: twin_q, target entropy
'auto', tau, initial_alpha; loss structure sac_torch_policy.py
actor/critic/alpha losses).  The TPU redesign mirrors DQN's: transitions
live in a [capacity, ...] device buffer via dynamic_update_slice under
lax.scan, polyak target sync replaces hard copies, and the alpha update is
a plain adam step on log_alpha — no data-dependent control flow under jit.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models.mlp import MLP
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayState, make_replay_state
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset

LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.lr = 3e-4
        self.buffer_size = 100_000
        self.learning_starts = 1_000
        self.tau = 0.005
        self.initial_alpha = 1.0
        self.target_entropy = "auto"  # -action_dim
        self.num_updates_per_iter = 8
        self.sac_batch_size = 256


class SquashedGaussianPolicy:
    """MLP → (mu, log_std); tanh squash scaled to the action bounds."""

    def __init__(self, obs_dim: int, action_dim: int, hiddens, low, high):
        self.net = MLP(tuple(hiddens), 2 * action_dim, name="pi")
        self.action_dim = action_dim
        self.scale = (high - low) / 2.0
        self.center = (high + low) / 2.0

    def init(self, key, obs):
        return self.net.init(key, obs)

    def dist_params(self, params, obs):
        out = self.net.apply(params, obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample(self, params, obs, key):
        """Reparameterized sample + log-prob with the tanh correction."""
        mu, log_std = self.dist_params(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        # Gaussian logp minus the tanh change-of-variables term
        # (numerically stable form: log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x))).
        logp = jnp.sum(
            -0.5 * (eps ** 2) - log_std - 0.5 * jnp.log(2 * jnp.pi),
            axis=-1)
        logp = logp - jnp.sum(
            2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)),
            axis=-1)
        # Affine change of variables for the bound scaling: without the
        # -log(scale) term the density is off by log(scale) per action dim,
        # which skews the alpha controller's entropy target.
        logp = logp - jnp.sum(
            jnp.broadcast_to(jnp.log(self.scale), (self.action_dim,)))
        action = jnp.tanh(pre) * self.scale + self.center
        return action, logp

    def mode(self, params, obs):
        mu, _ = self.dist_params(params, obs)
        return jnp.tanh(mu) * self.scale + self.center


class TwinQ:
    """Two independent Q(s, a) heads (reference: twin_q=True)."""

    def __init__(self, hiddens):
        self.q1 = MLP(tuple(hiddens), 1, name="q1")
        self.q2 = MLP(tuple(hiddens), 1, name="q2")

    def init(self, key, obs, action):
        k1, k2 = jax.random.split(key)
        x = jnp.concatenate([obs, action], axis=-1)
        return {"q1": self.q1.init(k1, x), "q2": self.q2.init(k2, x)}

    def apply(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return (self.q1.apply(params["q1"], x)[..., 0],
                self.q2.apply(params["q2"], x)[..., 0])


class SACState(NamedTuple):
    pi_params: Any
    q_params: Any
    q_target: Any
    log_alpha: jax.Array
    pi_opt: Any
    q_opt: Any
    a_opt: Any
    env_states: Any
    obs: jax.Array
    rng: jax.Array
    replay: ReplayState
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array


def make_sac_losses(pi, q, config, target_entropy):
    """The three SAC losses over an explicit minibatch — shared by the
    anakin path (replay-state batches) and the actor path (host-sampled
    batches), so the math exists once."""
    def q_loss(q_params, q_target, pi_params, log_alpha, batch, key):
        next_a, next_logp = pi.sample(pi_params, batch["next_obs"], key)
        tq1, tq2 = q.apply(q_target, batch["next_obs"], next_a)
        alpha = jnp.exp(log_alpha)
        target_v = jnp.minimum(tq1, tq2) - alpha * next_logp
        target = batch["rewards"] + config.gamma * (1 - batch["dones"]) \
            * jax.lax.stop_gradient(target_v)
        q1, q2 = q.apply(q_params, batch["obs"], batch["actions"])
        return jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

    def pi_loss(pi_params, q_params, log_alpha, batch, key):
        a, logp = pi.sample(pi_params, batch["obs"], key)
        q1, q2 = q.apply(q_params, batch["obs"], a)
        alpha = jnp.exp(log_alpha)
        return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

    def alpha_loss(log_alpha, logp):
        return -jnp.mean(log_alpha
                         * jax.lax.stop_gradient(logp + target_entropy))

    return q_loss, pi_loss, alpha_loss


def make_anakin_sac(config: SACConfig):
    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    adim = env.action_dim
    low = jnp.asarray(env.action_low, jnp.float32)
    high = jnp.asarray(env.action_high, jnp.float32)
    pi = SquashedGaussianPolicy(env.obs_dim, adim, config.hiddens, low, high)
    q = TwinQ(config.hiddens)
    target_entropy = (-float(adim) if config.target_entropy == "auto"
                      else float(config.target_entropy))
    def make_tx():
        parts = []
        if config.grad_clip:
            parts.append(optax.clip_by_global_norm(config.grad_clip))
        parts.append(optax.adam(config.lr))
        return optax.chain(*parts)

    pi_tx, q_tx, a_tx = make_tx(), make_tx(), make_tx()

    N, T = config.num_envs, config.unroll_length
    n_insert = N * T

    def init_fn(seed: int = 0) -> SACState:
        rng = jax.random.PRNGKey(seed)
        rng, k_pi, k_q, k_env = jax.random.split(rng, 4)
        env_states, obs = vector_reset(env, k_env, N)
        pi_params = pi.init(k_pi, obs)
        a0 = jnp.zeros((N, adim))
        q_params = q.init(k_q, obs, a0)
        replay = make_replay_state(config.buffer_size, n_insert,
                                   env.obs_dim, action_shape=(adim,),
                                   action_dtype=jnp.float32)
        return SACState(
            pi_params, q_params, q_params,
            jnp.log(jnp.asarray(config.initial_alpha, jnp.float32)),
            pi_tx.init(pi_params), q_tx.init(q_params),
            a_tx.init(jnp.zeros(())), env_states, obs, rng, replay,
            jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    from ray_tpu.rllib.algorithms.dqn import (_replay_insert,
                                              make_offpolicy_rollout)

    rollout_step = make_offpolicy_rollout(
        env, lambda p, obs, key: pi.sample(p, obs, key)[0])

    q_loss, pi_loss, alpha_loss = make_sac_losses(pi, q, config,
                                                  target_entropy)

    def train_step(state: SACState) -> Tuple[SACState, Dict[str, jax.Array]]:
        carry = (state.pi_params, state.env_states, state.obs, state.rng,
                 state.ep_return, state.done_return_sum, state.done_count)
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        (pi_params, env_states, obs, rng, ep_ret, dsum, dcnt) = carry
        flat = {k: v.reshape((n_insert,) + v.shape[2:])
                for k, v in traj.items()}
        replay = _replay_insert(state.replay, flat)

        def update(carry, key):
            (pi_params, q_params, q_target, log_alpha, pi_opt, q_opt,
             a_opt) = carry
            k_idx, k_q, k_pi = jax.random.split(key, 3)
            idx = jax.random.randint(k_idx, (config.sac_batch_size,), 0,
                                     jnp.maximum(replay.size, 1))
            batch = {k: getattr(replay, k)[idx]
                     for k in ("obs", "actions", "rewards", "next_obs",
                               "dones")}
            ql, q_grads = jax.value_and_grad(q_loss)(
                q_params, q_target, pi_params, log_alpha, batch, k_q)
            qu, q_opt = q_tx.update(q_grads, q_opt)
            q_params = optax.apply_updates(q_params, qu)
            (pl, logp), pi_grads = jax.value_and_grad(pi_loss, has_aux=True)(
                pi_params, q_params, log_alpha, batch, k_pi)
            pu, pi_opt = pi_tx.update(pi_grads, pi_opt)
            pi_params = optax.apply_updates(pi_params, pu)
            al, a_grad = jax.value_and_grad(alpha_loss)(log_alpha, logp)
            au, a_opt = a_tx.update(a_grad, a_opt)
            log_alpha = optax.apply_updates(log_alpha, au)
            tau = config.tau
            q_target = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, q_target, q_params)
            return (pi_params, q_params, q_target, log_alpha, pi_opt,
                    q_opt, a_opt), (ql, pl, al)

        rng, k = jax.random.split(rng)
        keys = jax.random.split(k, config.num_updates_per_iter)
        warm = replay.size >= config.learning_starts
        new_carry, (qls, pls, als) = jax.lax.scan(
            update, (pi_params, state.q_params, state.q_target,
                     state.log_alpha, state.pi_opt, state.q_opt,
                     state.a_opt), keys)
        old_carry = (pi_params, state.q_params, state.q_target,
                     state.log_alpha, state.pi_opt, state.q_opt, state.a_opt)
        # Before learning_starts: collect only, discard the updates.
        (pi_params, q_params, q_target, log_alpha, pi_opt, q_opt,
         a_opt) = jax.tree_util.tree_map(
            lambda new, old: jnp.where(warm, new, old), new_carry, old_carry)

        new_state = SACState(pi_params, q_params, q_target, log_alpha,
                             pi_opt, q_opt, a_opt, env_states, obs, rng,
                             replay, ep_ret, dsum, dcnt)
        metrics = {
            "critic_loss": qls.mean(), "actor_loss": pls.mean(),
            "alpha_loss": als.mean(), "alpha": jnp.exp(log_alpha),
            "replay_size": replay.size,
            "episode_return_sum": dsum, "episode_count": dcnt,
        }
        return new_state, metrics

    return pi, init_fn, jax.jit(train_step), n_insert


class SAC(Algorithm):
    _default_config_cls = SACConfig

    def _setup_anakin(self):
        (self.module, init_fn, self._train_step,
         self._steps_per_iter) = make_anakin_sac(self.config)
        self._anakin_state = init_fn(self.config.seed)

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    # -------- actor mode: CPU rollout actors -> host replay -> learner
    # (the Ape-X topology; reference: multi_gpu_learner_thread.py:20) ----
    def _setup_actor_mode(self):
        import cloudpickle
        import numpy as np

        from ray_tpu.rllib.env.py_envs import make_py_env
        from ray_tpu.rllib.execution.replay_plane import ReplayPlane
        from ray_tpu.rllib.evaluation.worker_set import (
            OffPolicyRolloutWorker,
            WorkerSet,
        )

        cfg = self.config
        probe = make_py_env(cfg.env)
        adim = getattr(probe, "action_dim", None)
        if adim is None:
            raise ValueError(
                f"SAC needs a continuous (Box) action env; {cfg.env!r} "
                "is discrete")
        obs_dim = probe.obs_dim
        low = jnp.asarray(probe.action_low, jnp.float32)
        high = jnp.asarray(probe.action_high, jnp.float32)
        pi = SquashedGaussianPolicy(obs_dim, adim, cfg.hiddens, low, high)
        q = TwinQ(cfg.hiddens)
        self.module = pi
        target_entropy = (-float(adim) if cfg.target_entropy == "auto"
                          else float(cfg.target_entropy))
        rng = jax.random.PRNGKey(cfg.seed)
        k_pi, k_q = jax.random.split(rng)
        z = jnp.zeros((1, obs_dim))
        self._pi_params = pi.init(k_pi, z)
        self._q_params = q.init(k_q, z, jnp.zeros((1, adim)))
        self._q_target = self._q_params
        self._log_alpha = jnp.log(jnp.asarray(cfg.initial_alpha,
                                              jnp.float32))

        def make_tx():
            parts = []
            if cfg.grad_clip:
                parts.append(optax.clip_by_global_norm(cfg.grad_clip))
            parts.append(optax.adam(cfg.lr))
            return optax.chain(*parts)

        pi_tx, q_tx, a_tx = make_tx(), make_tx(), make_tx()
        self._pi_opt = pi_tx.init(self._pi_params)
        self._q_opt = q_tx.init(self._q_params)
        self._a_opt = a_tx.init(self._log_alpha)
        self._env_steps = 0
        self._rb = ReplayPlane.from_config(cfg)

        hiddens = tuple(cfg.hiddens)
        low_l = np.asarray(probe.action_low).tolist()
        high_l = np.asarray(probe.action_high).tolist()

        def act_factory():
            import jax.numpy as _jnp

            from ray_tpu.rllib.algorithms.sac import (
                SquashedGaussianPolicy as _Pi,
            )

            apol = _Pi(obs_dim, adim, hiddens,
                       _jnp.asarray(low_l, _jnp.float32),
                       _jnp.asarray(high_l, _jnp.float32))

            def act(params, obs, key, _unused):
                return apol.sample(params, obs, key)[0]

            return act

        blob = cloudpickle.dumps(act_factory)

        def factory(i):
            return OffPolicyRolloutWorker.options(max_restarts=1).remote(
                cfg.env, blob, i, cfg.num_envs_per_worker,
                cfg.rollout_fragment_length, cfg.seed)

        self.workers = WorkerSet(cfg, None, worker_factory=factory)
        self.workers.sync_weights(jax.device_get(self._pi_params))

        q_loss, pi_loss, alpha_loss = make_sac_losses(pi, q, cfg,
                                                      target_entropy)

        def update_many(pi_params, q_params, q_target, log_alpha, pi_opt,
                        q_opt, a_opt, batches, keys):
            def one(carry, xs):
                (pi_params, q_params, q_target, log_alpha, pi_opt, q_opt,
                 a_opt) = carry
                batch, key = xs
                k_q, k_pi = jax.random.split(key)
                ql, q_grads = jax.value_and_grad(q_loss)(
                    q_params, q_target, pi_params, log_alpha, batch, k_q)
                qu, q_opt = q_tx.update(q_grads, q_opt)
                q_params = optax.apply_updates(q_params, qu)
                (pl, logp), pi_grads = jax.value_and_grad(
                    pi_loss, has_aux=True)(pi_params, q_params, log_alpha,
                                           batch, k_pi)
                pu, pi_opt = pi_tx.update(pi_grads, pi_opt)
                pi_params = optax.apply_updates(pi_params, pu)
                al, a_grad = jax.value_and_grad(alpha_loss)(log_alpha, logp)
                au, a_opt = a_tx.update(a_grad, a_opt)
                log_alpha = optax.apply_updates(log_alpha, au)
                tau = cfg.tau
                q_target = jax.tree_util.tree_map(
                    lambda t, p_: (1 - tau) * t + tau * p_, q_target,
                    q_params)
                return (pi_params, q_params, q_target, log_alpha, pi_opt,
                        q_opt, a_opt), (ql, pl, al)

            carry = (pi_params, q_params, q_target, log_alpha, pi_opt,
                     q_opt, a_opt)
            carry, (qls, pls, als) = jax.lax.scan(one, carry,
                                                  (batches, keys))
            return carry + (qls, pls, als)

        self._update_many = jax.jit(update_many)
        self._host_rng = np.random.default_rng(cfg.seed)

    def _sync_params(self):
        return self._pi_params

    def _training_step_actor(self):
        from ray_tpu.rllib.algorithms.dqn import run_actor_replay_iter

        def do_updates(stacked, keys):
            (self._pi_params, self._q_params, self._q_target,
             self._log_alpha, self._pi_opt, self._q_opt, self._a_opt,
             qls, pls, als) = self._update_many(
                self._pi_params, self._q_params, self._q_target,
                self._log_alpha, self._pi_opt, self._q_opt, self._a_opt,
                stacked, keys)
            return {"critic_loss": float(qls.mean()),
                    "actor_loss": float(pls.mean()),
                    "alpha": float(jnp.exp(self._log_alpha))}

        return run_actor_replay_iter(self, 0.0,
                                     self.config.sac_batch_size,
                                     do_updates)


    # SACState has multiple param trees — override the Trainable protocol's
    # single-tree default (algorithm.py:52).
    def save_checkpoint(self) -> "Checkpoint":
        from ray_tpu.air.checkpoint import Checkpoint

        s = self._anakin_state
        return Checkpoint.from_pytree(
            {"pi": s.pi_params, "q": s.q_params, "q_target": s.q_target,
             "log_alpha": s.log_alpha},
            extra={"iteration": self.iteration})

    def load_checkpoint(self, checkpoint):
        tree = checkpoint.to_pytree()
        self.iteration = checkpoint.extra().get("iteration", 0)
        self._anakin_state = self._anakin_state._replace(
            pi_params=tree["pi"], q_params=tree["q"],
            q_target=tree["q_target"], log_alpha=tree["log_alpha"])
