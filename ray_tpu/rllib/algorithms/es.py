"""Evolution Strategies (OpenAI-ES).

Reference: rllib/algorithms/es/es.py — a head process fans noise seeds to
CPU workers, each perturbs the policy, runs an episode, and returns a
scalar fitness; the head reconstructs the noise from seeds and applies
the rank-weighted update.  TPU-first redesign: the whole generation is
ONE jitted program — the population is a leading axis, rollouts are
vmapped jax envs, and the antithetic rank-weighted gradient is two
matmuls.  No seed plumbing, no noise table, no worker fleet: the
population dimension IS the parallelism, and it maps onto the MXU/VPU
instead of a process pool.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env.jax_envs import make_jax_env
from ray_tpu.models.mlp import MLP


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=ES)
        # Reference knobs (es.py DEFAULT_CONFIG): episodes_per_batch /
        # noise_stdev / stepsize / l2_coeff.
        self.population_size = 256       # antithetic pairs: must be even
        self.noise_stdev = 0.05
        self.lr = 0.02
        self.l2_coeff = 0.005
        self.episode_length = 200


class ESState(NamedTuple):
    flat_params: jax.Array
    opt_state: Any
    rng: jax.Array
    gen: jax.Array


def _centered_ranks(x: jax.Array) -> jax.Array:
    """Fitness shaping (reference: es_utils compute_centered_ranks)."""
    ranks = jnp.argsort(jnp.argsort(x)).astype(jnp.float32)
    return ranks / (x.shape[0] - 1) - 0.5


class ES(Algorithm):
    _default_config_cls = ESConfig

    def _setup_anakin(self):
        config = self.config
        if config.population_size % 2:
            raise ValueError("population_size must be even (antithetic)")
        env = make_jax_env(config.env) if isinstance(config.env, str) \
            else config.env
        net = MLP(features=tuple(config.hiddens),
                  out_dim=env.num_actions)
        key = jax.random.PRNGKey(config.seed)
        st0, obs0 = env.reset(key)
        params = net.init(key, obs0[None])
        from jax.flatten_util import ravel_pytree

        flat0, unravel = ravel_pytree(params)
        self._unravel = unravel
        self._net = net
        dim = flat0.shape[0]
        half = config.population_size // 2
        sigma, T = config.noise_stdev, config.episode_length
        tx = optax.chain(
            optax.add_decayed_weights(config.l2_coeff),
            optax.sgd(config.lr, momentum=0.9))

        def episode_return(flat, rng):
            """Deterministic-policy episode return (the ES fitness)."""
            p = unravel(flat)

            def step(carry, _):
                st, obs, ret, alive, rng = carry
                rng, k = jax.random.split(rng)
                act = jnp.argmax(net.apply(p, obs[None])[0])
                st, obs, r, done, _ = env.step(st, act, k)
                ret = ret + r * alive
                alive = alive * (1.0 - done.astype(jnp.float32))
                return (st, obs, ret, alive, rng), None

            rng, k = jax.random.split(rng)
            st, obs = env.reset(k)
            (_, _, ret, _, _), _ = jax.lax.scan(
                step, (st, obs, 0.0, 1.0, rng), None, length=T)
            return ret

        def train_step(state: ESState):
            rng, k_noise, k_ep = jax.random.split(state.rng, 3)
            eps = jax.random.normal(k_noise, (half, dim))
            pop = jnp.concatenate([state.flat_params + sigma * eps,
                                   state.flat_params - sigma * eps])
            fit = jax.vmap(episode_return)(
                pop, jax.random.split(k_ep, 2 * half))
            ranks = _centered_ranks(fit)
            # Antithetic estimator: (R+ - R-) weighted noise.
            w = ranks[:half] - ranks[half:]
            grad = -(w @ eps) / (half * sigma)  # ascent via optimizer
            updates, opt_state = tx.update(grad, state.opt_state,
                                           state.flat_params)
            flat = optax.apply_updates(state.flat_params, updates)
            metrics = {"episode_reward_mean": fit.mean(),
                       "fitness_max": fit.max(),
                       "fitness_std": fit.std()}
            return ESState(flat, opt_state, rng, state.gen + 1), metrics

        self._anakin_state = ESState(flat0, tx.init(flat0),
                                     jax.random.PRNGKey(config.seed),
                                     jnp.zeros((), jnp.int32))
        self._train_step = jax.jit(train_step)
        self._steps_per_iter = config.population_size * T

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    # Checkpointing: the flat vector is the whole policy.
    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_pytree(
            self._unravel(self._anakin_state.flat_params),
            extra={"iteration": self.iteration})

    def load_checkpoint(self, checkpoint):
        from jax.flatten_util import ravel_pytree

        params = checkpoint.to_pytree()
        flat, _ = ravel_pytree(params)
        self.iteration = checkpoint.extra().get("iteration", 0)
        self._anakin_state = self._anakin_state._replace(
            flat_params=jnp.asarray(flat))
