"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Reference: rllib/algorithms/qmix/ (QMixTorchPolicy — per-agent Q
networks + a state-conditioned hypernetwork mixer whose non-negative
weights keep argmax_a Q_tot = per-agent argmaxes).  TPU-first redesign
on the array-axis multi-agent protocol (rllib/env/multi_agent.py):
agents are a leading axis, the per-agent net is weight-shared (agent id
rides in the observation), and rollout, replay, and the mixed TD update
compile into one anakin step.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env.multi_agent import (
    ma_vector_reset,
    ma_vector_step,
    make_ma_env,
)
from ray_tpu.models.mlp import MLP


class QMixConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=QMix)
        self.env = "CoordinationGame-v0"
        self.lr = 5e-4
        self.buffer_size = 20_000
        self.learning_starts = 500
        self.target_network_tau = 0.01
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 30_000
        self.num_updates_per_iter = 4
        self.qmix_batch_size = 128
        self.mixing_embed_dim = 32
        self.num_envs = 32
        self.unroll_length = 16


class Mixer:
    """Monotonic mixing network: Q_tot = w2(s)^T elu(W1(s) q + b1) + b2,
    with |W1|, |w2| enforcing dQ_tot/dQ_i >= 0 (reference:
    qmix/model.py QMixer)."""

    def __init__(self, num_agents: int, state_dim: int, embed: int):
        self.M, self.embed = num_agents, embed
        self.hyper_w1 = MLP(features=(64,), out_dim=num_agents * embed)
        self.hyper_b1 = MLP(features=(64,), out_dim=embed)
        self.hyper_w2 = MLP(features=(64,), out_dim=embed)
        self.hyper_b2 = MLP(features=(64,), out_dim=1)

    def init(self, key, state):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {"w1": self.hyper_w1.init(k1, state),
                "b1": self.hyper_b1.init(k2, state),
                "w2": self.hyper_w2.init(k3, state),
                "b2": self.hyper_b2.init(k4, state)}

    def apply(self, params, qs, state):
        """qs: [B, M] chosen per-agent values; state: [B, s]."""
        B = qs.shape[0]
        w1 = jnp.abs(self.hyper_w1.apply(params["w1"], state)).reshape(
            B, self.M, self.embed)
        b1 = self.hyper_b1.apply(params["b1"], state)
        w2 = jnp.abs(self.hyper_w2.apply(params["w2"], state))
        b2 = self.hyper_b2.apply(params["b2"], state)[:, 0]
        h = jax.nn.elu(jnp.einsum("bm,bme->be", qs, w1) + b1)
        return jnp.einsum("be,be->b", h, w2) + b2


class QMixState(NamedTuple):
    params: Any          # {"agent": ..., "mixer": ...}
    target_params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array       # [N, M, obs_dim]
    rng: jax.Array
    replay: Any          # dict of arrays
    env_steps: jax.Array
    ep_return: jax.Array  # [N]
    done_return_sum: jax.Array
    done_count: jax.Array


class QMix(Algorithm):
    _default_config_cls = QMixConfig

    def _setup_anakin(self):
        config = self.config
        env = make_ma_env(config.env) if isinstance(config.env, str) \
            else config.env
        M, A, obs_dim = env.num_agents, env.num_actions, env.obs_dim
        state_dim = M * obs_dim   # global state = concat agent obs
        N, T = config.num_envs, config.unroll_length
        qnet = MLP(features=tuple(config.hiddens), out_dim=A)
        mixer = Mixer(M, state_dim, config.mixing_embed_dim)
        gamma = config.gamma
        B = config.qmix_batch_size
        tx = optax.adam(config.lr)
        cap = max(config.buffer_size, N * T)
        cap = ((cap + N * T - 1) // (N * T)) * (N * T)

        def agent_qs(ap, obs):
            """obs [..., M, obs_dim] -> [..., M, A] (weight-shared)."""
            return qnet.apply(ap, obs)

        def td_loss(p, tp, batch):
            qs = agent_qs(p["agent"], batch["obs"])          # [B, M, A]
            chosen = jnp.take_along_axis(
                qs, batch["actions"][..., None], -1)[..., 0]  # [B, M]
            state = batch["obs"].reshape(B, state_dim)
            q_tot = mixer.apply(p["mixer"], chosen, state)
            nqs_online = agent_qs(p["agent"], batch["next_obs"])
            nqs_target = agent_qs(tp["agent"], batch["next_obs"])
            na = jnp.argmax(nqs_online, axis=-1)              # [B, M]
            nv = jnp.take_along_axis(nqs_target, na[..., None], -1)[..., 0]
            nstate = batch["next_obs"].reshape(B, state_dim)
            nq_tot = mixer.apply(tp["mixer"], nv, nstate)
            # CoordinationGame rewards are shared: the team reward is the
            # per-agent reward (identical across agents).
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * nq_tot
            return jnp.mean((q_tot - jax.lax.stop_gradient(target)) ** 2)

        def rollout(state, rng):
            def one(carry, _):
                env_states, obs, rng, ep_ret, dsum, dcnt, steps, ap = carry
                rng, k_eps, k_rand, k_step = jax.random.split(rng, 4)
                eps = jnp.clip(
                    1.0 - (1.0 - config.epsilon_final) * steps
                    / config.epsilon_decay_steps,
                    config.epsilon_final, 1.0)
                greedy = jnp.argmax(agent_qs(ap, obs), axis=-1)  # [N, M]
                rand = jax.random.randint(k_rand, (N, M), 0, A)
                act = jnp.where(
                    jax.random.uniform(k_eps, (N, M)) < eps, rand, greedy)
                env_states, next_obs, rew, done, _ = ma_vector_step(
                    env, env_states, act, k_step)
                team_r = rew[:, 0]   # shared reward
                ep_ret = ep_ret + team_r
                dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
                dcnt = dcnt + jnp.sum(done)
                ep_ret = jnp.where(done, 0.0, ep_ret)
                out = (obs, act, team_r, next_obs,
                       done.astype(jnp.float32))
                return (env_states, next_obs, rng, ep_ret, dsum, dcnt,
                        steps + N, ap), out

            carry = (state.env_states, state.obs, rng, state.ep_return,
                     state.done_return_sum, state.done_count,
                     state.env_steps, state.params["agent"])
            carry, tr = jax.lax.scan(one, carry, None, length=T)
            env_states, obs, _, ep_ret, dsum, dcnt, steps, _ = carry
            o, a, r, no, d = tr
            n = N * T
            flat = {"obs": o.reshape(n, M, obs_dim),
                    "actions": a.reshape(n, M),
                    "rewards": r.reshape(n),
                    "next_obs": no.reshape(n, M, obs_dim),
                    "dones": d.reshape(n)}
            return env_states, obs, ep_ret, dsum, dcnt, steps, flat

        def replay_insert(replay, flat):
            n = flat["rewards"].shape[0]
            pos = replay["pos"]
            out = {}
            for k, v in flat.items():
                out[k] = jax.lax.dynamic_update_slice(
                    replay[k], v, (pos,) + (0,) * (v.ndim - 1))
            out["pos"] = (pos + n) % cap
            out["size"] = jnp.minimum(replay["size"] + n, cap)
            return out

        def train_step(state: QMixState):
            rng, k_roll, k_q = jax.random.split(state.rng, 3)
            (env_states, obs, ep_ret, dsum, dcnt, steps,
             flat) = rollout(state, k_roll)
            replay = replay_insert(state.replay, flat)

            def q_update(carry, k):
                p, tp, opt = carry
                idx = jax.random.randint(
                    k, (B,), 0, jnp.maximum(replay["size"], 1))
                batch = {kk: replay[kk][idx]
                         for kk in ("obs", "actions", "rewards",
                                    "next_obs", "dones")}
                loss, grads = jax.value_and_grad(td_loss)(p, tp, batch)
                up, opt = tx.update(grads, opt, p)
                p = optax.apply_updates(p, up)
                tp = jax.tree.map(
                    lambda t, o: t * (1 - config.target_network_tau)
                    + o * config.target_network_tau, tp, p)
                return (p, tp, opt), loss

            warm = replay["size"] >= config.learning_starts
            (p, tp, opt), losses = jax.lax.scan(
                q_update,
                (state.params, state.target_params, state.opt_state),
                jax.random.split(k_q, config.num_updates_per_iter))
            p, tp, opt = jax.tree.map(
                lambda new, old: jnp.where(warm, new, old),
                (p, tp, opt),
                (state.params, state.target_params, state.opt_state))
            new_state = QMixState(p, tp, opt, env_states, obs, rng,
                                  replay, steps, ep_ret, dsum, dcnt)
            metrics = {"total_loss": losses.mean(),
                       "episode_return_sum": dsum,
                       "episode_count": dcnt}
            return new_state, metrics

        key = jax.random.PRNGKey(config.seed)
        k_q, k_m, k_env, k_rng = jax.random.split(key, 4)
        env_states, obs0 = ma_vector_reset(env, k_env, N)
        ap = qnet.init(k_q, obs0)
        mp = mixer.init(k_m, obs0.reshape(N, state_dim))
        params = {"agent": ap, "mixer": mp}
        replay0 = {
            "obs": jnp.zeros((cap, M, obs_dim), jnp.float32),
            "actions": jnp.zeros((cap, M), jnp.int32),
            "rewards": jnp.zeros((cap,), jnp.float32),
            "next_obs": jnp.zeros((cap, M, obs_dim), jnp.float32),
            "dones": jnp.zeros((cap,), jnp.float32),
            "pos": jnp.zeros((), jnp.int32),
            "size": jnp.zeros((), jnp.int32),
        }
        self._anakin_state = QMixState(
            params, jax.tree.map(lambda x: x, params), tx.init(params),
            env_states, obs0, k_rng, replay0, jnp.zeros((), jnp.int32),
            jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))
        self._train_step = jax.jit(train_step)
        self._steps_per_iter = N * T * M

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics
