"""TD3 / DDPG: deterministic-policy continuous control, anakin-style.

Reference: rllib/algorithms/ddpg/ (ddpg.py config surface: twin_q,
policy_delay, smooth_target_policy, target_noise/clip, tau,
ou/gaussian exploration) and rllib/algorithms/td3/td3.py (TD3 = DDPG
with twin_q=True, policy_delay=2, smooth_target_policy=True defaults —
the same relationship holds here).  Loss structure per
ddpg_torch_policy.py: critic regresses the polyak target network's
Bellman backup, actor ascends Q1 of its own action.

TPU redesign mirrors SAC's: env stepping, HBM replay, twin-Q and
delayed policy updates all inside ONE jitted step; the policy delay is
a counter-masked update (no data-dependent control flow under jit).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.mlp import MLP
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import (ReplayState, _replay_insert,
                                          make_offpolicy_rollout,
                                          make_replay_state)
from ray_tpu.rllib.algorithms.sac import TwinQ
from ray_tpu.rllib.env.jax_envs import make_jax_env, vector_reset


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=TD3)
        self.lr = 1e-3
        self.buffer_size = 100_000
        self.learning_starts = 1_000
        self.tau = 0.005
        self.twin_q = True
        self.policy_delay = 2
        self.smooth_target_policy = True
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.exploration_noise = 0.1
        self.num_updates_per_iter = 8
        self.td3_batch_size = 256


class DDPGConfig(TD3Config):
    """Reference relationship inverted but equivalent: DDPG is TD3 minus
    the three TD3 tricks (rllib/algorithms/td3/td3.py defaults)."""

    def __init__(self):
        super().__init__()
        self.algo_class = DDPG
        self.twin_q = False
        self.policy_delay = 1
        self.smooth_target_policy = False


class DeterministicPolicy:
    """MLP → tanh-squashed action scaled to the bounds."""

    def __init__(self, action_dim: int, hiddens, low, high):
        self.net = MLP(tuple(hiddens), action_dim, name="pi")
        self.scale = (high - low) / 2.0
        self.center = (high + low) / 2.0

    def init(self, key, obs):
        return self.net.init(key, obs)

    def apply(self, params, obs):
        return jnp.tanh(self.net.apply(params, obs)) * self.scale \
            + self.center

    # Algorithm.compute_single_action protocol
    def mode(self, params, obs):
        return self.apply(params, obs)


class TD3State(NamedTuple):
    pi_params: Any
    pi_target: Any
    q_params: Any
    q_target: Any
    pi_opt: Any
    q_opt: Any
    update_count: jax.Array
    env_states: Any
    obs: jax.Array
    rng: jax.Array
    replay: ReplayState
    ep_return: jax.Array
    done_return_sum: jax.Array
    done_count: jax.Array


def make_td3_losses(pi, q, config, scale, low, high):
    """TD3's two losses over an explicit minibatch — shared by the anakin
    and actor paths so the Bellman-target math exists once."""
    def q_loss(q_params, q_target, pi_target, batch, key):
        next_a = pi.apply(pi_target, batch["next_obs"])
        if config.smooth_target_policy:
            # Target policy smoothing (TD3 trick #3): clipped noise on the
            # target action regularizes the critic against sharp Q peaks.
            eps = jnp.clip(
                config.target_noise * scale
                * jax.random.normal(key, next_a.shape),
                -config.target_noise_clip * scale,
                config.target_noise_clip * scale)
            next_a = jnp.clip(next_a + eps, low, high)
        tq1, tq2 = q.apply(q_target, batch["next_obs"], next_a)
        target_v = jnp.minimum(tq1, tq2) if config.twin_q else tq1
        target = batch["rewards"] + config.gamma * (1 - batch["dones"]) \
            * jax.lax.stop_gradient(target_v)
        q1, q2 = q.apply(q_params, batch["obs"], batch["actions"])
        loss = jnp.mean((q1 - target) ** 2)
        if config.twin_q:
            loss = loss + jnp.mean((q2 - target) ** 2)
        return loss

    def pi_loss(pi_params, q_params, batch):
        a = pi.apply(pi_params, batch["obs"])
        q1, _ = q.apply(q_params, batch["obs"], a)
        return -jnp.mean(q1)

    return q_loss, pi_loss


def make_anakin_td3(config: TD3Config):
    env = make_jax_env(config.env) if isinstance(config.env, str) \
        else config.env
    adim = env.action_dim
    low = jnp.asarray(env.action_low, jnp.float32)
    high = jnp.asarray(env.action_high, jnp.float32)
    scale = (high - low) / 2.0
    pi = DeterministicPolicy(adim, config.hiddens, low, high)
    q = TwinQ(config.hiddens)

    def make_tx():
        parts = []
        if config.grad_clip:
            parts.append(optax.clip_by_global_norm(config.grad_clip))
        parts.append(optax.adam(config.lr))
        return optax.chain(*parts)

    pi_tx, q_tx = make_tx(), make_tx()
    N, T = config.num_envs, config.unroll_length
    n_insert = N * T

    def init_fn(seed: int = 0) -> TD3State:
        rng = jax.random.PRNGKey(seed)
        rng, k_pi, k_q, k_env = jax.random.split(rng, 4)
        env_states, obs = vector_reset(env, k_env, N)
        pi_params = pi.init(k_pi, obs)
        q_params = q.init(k_q, obs, jnp.zeros((N, adim)))
        replay = make_replay_state(config.buffer_size, n_insert,
                                   env.obs_dim, action_shape=(adim,),
                                   action_dtype=jnp.float32)
        return TD3State(pi_params, pi_params, q_params, q_params,
                        pi_tx.init(pi_params), q_tx.init(q_params),
                        jnp.zeros((), jnp.int32), env_states, obs, rng,
                        replay, jnp.zeros(N), jnp.zeros(()), jnp.zeros(()))

    def explore(pi_params, obs, key):
        action = pi.apply(pi_params, obs)
        noise = config.exploration_noise * scale \
            * jax.random.normal(key, action.shape)
        return jnp.clip(action + noise, low, high)

    rollout_step = make_offpolicy_rollout(env, explore)

    q_loss, pi_loss = make_td3_losses(pi, q, config, scale, low, high)

    def train_step(state: TD3State) -> Tuple[TD3State, Dict[str, jax.Array]]:
        carry = (state.pi_params, state.env_states, state.obs, state.rng,
                 state.ep_return, state.done_return_sum, state.done_count)
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        pi_params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
        flat = {k: v.reshape((n_insert,) + v.shape[2:])
                for k, v in traj.items()}
        replay = _replay_insert(state.replay, flat)

        def update(carry, key):
            (pi_params, pi_target, q_params, q_target, pi_opt, q_opt,
             count) = carry
            k_idx, k_q = jax.random.split(key)
            idx = jax.random.randint(k_idx, (config.td3_batch_size,), 0,
                                     jnp.maximum(replay.size, 1))
            batch = {k: getattr(replay, k)[idx]
                     for k in ("obs", "actions", "rewards", "next_obs",
                               "dones")}
            ql, q_grads = jax.value_and_grad(q_loss)(
                q_params, q_target, pi_target, batch, k_q)
            qu, q_opt = q_tx.update(q_grads, q_opt)
            q_params = optax.apply_updates(q_params, qu)
            # Delayed policy update (TD3 trick #2): grads computed every
            # step, applied only when count % policy_delay == 0 — a masked
            # update keeps the scan shape static.
            pl, pi_grads = jax.value_and_grad(pi_loss)(
                pi_params, q_params, batch)
            pu, new_pi_opt = pi_tx.update(pi_grads, pi_opt)
            new_pi = optax.apply_updates(pi_params, pu)
            apply_pi = (count % config.policy_delay) == 0
            pi_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(apply_pi, n, o), new_pi, pi_params)
            pi_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(apply_pi, n, o), new_pi_opt, pi_opt)
            tau = config.tau
            polyak = lambda t, p: (1 - tau) * t + tau * p  # noqa: E731
            q_target = jax.tree_util.tree_map(polyak, q_target, q_params)
            pi_target = jax.tree_util.tree_map(
                lambda t, p: jnp.where(apply_pi, polyak(t, p), t),
                pi_target, pi_params)
            return (pi_params, pi_target, q_params, q_target, pi_opt,
                    q_opt, count + 1), (ql, pl)

        rng, k = jax.random.split(rng)
        keys = jax.random.split(k, config.num_updates_per_iter)
        warm = replay.size >= config.learning_starts
        start = (pi_params, state.pi_target, state.q_params, state.q_target,
                 state.pi_opt, state.q_opt, state.update_count)
        new_carry, (qls, pls) = jax.lax.scan(update, start, keys)
        # Before learning_starts: collect only, discard the updates.
        (pi_params, pi_target, q_params, q_target, pi_opt, q_opt,
         count) = jax.tree_util.tree_map(
            lambda new, old: jnp.where(warm, new, old), new_carry, start)

        new_state = TD3State(pi_params, pi_target, q_params, q_target,
                             pi_opt, q_opt, count, env_states, obs, rng,
                             replay, ep_ret, dsum, dcnt)
        metrics = {"critic_loss": qls.mean(), "actor_loss": pls.mean(),
                   "replay_size": replay.size,
                   "episode_return_sum": dsum, "episode_count": dcnt}
        return new_state, metrics

    return pi, init_fn, jax.jit(train_step), n_insert


class TD3(Algorithm):
    _default_config_cls = TD3Config

    def _setup_anakin(self):
        (self.module, init_fn, self._train_step,
         self._steps_per_iter) = make_anakin_td3(self.config)
        self._anakin_state = init_fn(self.config.seed)

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    # -------- actor mode (Ape-X topology; see dqn.py/sac.py) --------
    def _setup_actor_mode(self):
        import cloudpickle
        import numpy as np

        from ray_tpu.rllib.env.py_envs import make_py_env
        from ray_tpu.rllib.execution.replay_plane import ReplayPlane
        from ray_tpu.rllib.evaluation.worker_set import (
            OffPolicyRolloutWorker,
            WorkerSet,
        )

        cfg = self.config
        probe = make_py_env(cfg.env)
        adim = getattr(probe, "action_dim", None)
        if adim is None:
            raise ValueError(
                f"TD3 needs a continuous (Box) action env; {cfg.env!r} "
                "is discrete")
        obs_dim = probe.obs_dim
        low = jnp.asarray(probe.action_low, jnp.float32)
        high = jnp.asarray(probe.action_high, jnp.float32)
        scale = (high - low) / 2.0
        pi = DeterministicPolicy(adim, cfg.hiddens, low, high)
        q = TwinQ(cfg.hiddens)
        self.module = pi
        rng = jax.random.PRNGKey(cfg.seed)
        k_pi, k_q = jax.random.split(rng)
        z = jnp.zeros((1, obs_dim))
        self._pi_params = pi.init(k_pi, z)
        self._pi_target = self._pi_params
        self._q_params = q.init(k_q, z, jnp.zeros((1, adim)))
        self._q_target = self._q_params

        def make_tx():
            parts = []
            if cfg.grad_clip:
                parts.append(optax.clip_by_global_norm(cfg.grad_clip))
            parts.append(optax.adam(cfg.lr))
            return optax.chain(*parts)

        pi_tx, q_tx = make_tx(), make_tx()
        self._pi_opt = pi_tx.init(self._pi_params)
        self._q_opt = q_tx.init(self._q_params)
        self._count = jnp.zeros((), jnp.int32)
        self._env_steps = 0
        self._rb = ReplayPlane.from_config(cfg)
        self._host_rng = np.random.default_rng(cfg.seed)

        hiddens = tuple(cfg.hiddens)
        low_l = np.asarray(probe.action_low).tolist()
        high_l = np.asarray(probe.action_high).tolist()

        def act_factory():
            import jax as _jax
            import jax.numpy as _jnp

            from ray_tpu.rllib.algorithms.td3 import (
                DeterministicPolicy as _Pi,
            )

            lo = _jnp.asarray(low_l, _jnp.float32)
            hi = _jnp.asarray(high_l, _jnp.float32)
            sc = (hi - lo) / 2.0
            apol = _Pi(adim, hiddens, lo, hi)

            def act(params, obs, key, noise_scale):
                a = apol.apply(params, obs)
                noise = noise_scale * sc * _jax.random.normal(key, a.shape)
                return _jnp.clip(a + noise, lo, hi)

            return act

        blob = cloudpickle.dumps(act_factory)

        def factory(i):
            return OffPolicyRolloutWorker.options(max_restarts=1).remote(
                cfg.env, blob, i, cfg.num_envs_per_worker,
                cfg.rollout_fragment_length, cfg.seed)

        self.workers = WorkerSet(cfg, None, worker_factory=factory)
        self.workers.sync_weights(jax.device_get(self._pi_params))

        q_loss, pi_loss = make_td3_losses(pi, q, cfg, scale, low, high)

        def update_many(pi_params, pi_target, q_params, q_target, pi_opt,
                        q_opt, count, batches, keys):
            def one(carry, xs):
                (pi_params, pi_target, q_params, q_target, pi_opt, q_opt,
                 count) = carry
                batch, key = xs
                ql, q_grads = jax.value_and_grad(q_loss)(
                    q_params, q_target, pi_target, batch, key)
                qu, q_opt = q_tx.update(q_grads, q_opt)
                q_params = optax.apply_updates(q_params, qu)
                pl, pi_grads = jax.value_and_grad(pi_loss)(
                    pi_params, q_params, batch)
                pu, new_pi_opt = pi_tx.update(pi_grads, pi_opt)
                new_pi = optax.apply_updates(pi_params, pu)
                apply_pi = (count % cfg.policy_delay) == 0
                pi_params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(apply_pi, n, o), new_pi,
                    pi_params)
                pi_opt = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(apply_pi, n, o), new_pi_opt,
                    pi_opt)
                tau = cfg.tau
                polyak = lambda t, p_: (1 - tau) * t + tau * p_  # noqa: E731
                q_target = jax.tree_util.tree_map(polyak, q_target,
                                                  q_params)
                pi_target = jax.tree_util.tree_map(
                    lambda t, p_: jnp.where(apply_pi, polyak(t, p_), t),
                    pi_target, pi_params)
                return (pi_params, pi_target, q_params, q_target, pi_opt,
                        q_opt, count + 1), (ql, pl)

            carry = (pi_params, pi_target, q_params, q_target, pi_opt,
                     q_opt, count)
            carry, (qls, pls) = jax.lax.scan(one, carry, (batches, keys))
            return carry + (qls, pls)

        self._update_many = jax.jit(update_many)

    def _sync_params(self):
        return self._pi_params

    def _training_step_actor(self):
        from ray_tpu.rllib.algorithms.dqn import run_actor_replay_iter

        def do_updates(stacked, keys):
            (self._pi_params, self._pi_target, self._q_params,
             self._q_target, self._pi_opt, self._q_opt, self._count,
             qls, pls) = self._update_many(
                self._pi_params, self._pi_target, self._q_params,
                self._q_target, self._pi_opt, self._q_opt, self._count,
                stacked, keys)
            return {"critic_loss": float(qls.mean()),
                    "actor_loss": float(pls.mean())}

        return run_actor_replay_iter(self, self.config.exploration_noise,
                                     self.config.td3_batch_size,
                                     do_updates)


    def save_checkpoint(self):
        """Full training state: params + BOTH optimizer moment trees +
        update_count (the policy-delay phase).  Replay contents stay
        excluded by design — a resumed run restarts collection, which is
        documented resume behavior (fresh transitions under the restored
        policy), not silent state loss."""
        from ray_tpu.air.checkpoint import Checkpoint

        s = self._anakin_state
        return Checkpoint.from_pytree(
            {"pi": s.pi_params, "pi_target": s.pi_target,
             "q": s.q_params, "q_target": s.q_target,
             "pi_opt": s.pi_opt, "q_opt": s.q_opt,
             "update_count": s.update_count},
            extra={"iteration": self.iteration})

    def load_checkpoint(self, checkpoint):
        tree = checkpoint.to_pytree()
        self.iteration = checkpoint.extra().get("iteration", 0)
        s = self._anakin_state
        self._anakin_state = s._replace(
            pi_params=tree["pi"], pi_target=tree["pi_target"],
            q_params=tree["q"], q_target=tree["q_target"],
            # Older checkpoints (pre r4) lack optimizer state: keep the
            # freshly-initialized moments rather than failing the restore.
            pi_opt=tree.get("pi_opt", s.pi_opt),
            q_opt=tree.get("q_opt", s.q_opt),
            update_count=tree.get("update_count", s.update_count))


class DDPG(TD3):
    _default_config_cls = DDPGConfig
