"""Multi-agent PPO with parameter sharing, anakin-style.

Reference: RLlib's multi-agent training with a shared policy
(policy_mapping_fn returning one policy id for every agent,
rllib/algorithms/algorithm_config.py multi_agent()).  The TPU redesign
folds the agent axis into the batch: the rollout is a [G, M] scan (G
games, M agents) on device, the shared policy evaluates all G*M agent
observations in one forward, GAE runs per agent stream with the game's
done broadcast, and the standard clipped-surrogate SGD consumes the
flattened [T*G*M] batch.  Independent per-agent policies are the
MultiAgentBatch/policy_mapping path on the actor stack; this module is
the high-throughput shared-weights form.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
import functools

from ray_tpu.rllib.algorithms.ppo import ppo_loss
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.multi_agent import (
    ma_vector_reset,
    ma_vector_step,
    make_ma_env,
)
from ray_tpu.rllib.evaluation.postprocessing import gae_jax


class MAPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=MAPPO)
        self.num_envs = 32  # games


class MAState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any
    obs: jax.Array            # [G, M, d]
    rng: jax.Array
    ep_return: jax.Array      # [G] summed over agents
    done_return_sum: jax.Array
    done_count: jax.Array


def make_anakin_mappo(config: MAPPOConfig):
    env = make_ma_env(config.env) if isinstance(config.env, str) \
        else config.env
    G, M, T = config.num_envs, env.num_agents, config.unroll_length
    spec = RLModuleSpec(obs_dim=env.obs_dim, num_actions=env.num_actions,
                        hiddens=tuple(config.hiddens))
    module = spec.build()
    tx_parts = []
    if config.grad_clip:
        tx_parts.append(optax.clip_by_global_norm(config.grad_clip))
    tx_parts.append(optax.adam(config.lr))
    tx = optax.chain(*tx_parts)

    flat_n = G * M
    batch_total = T * flat_n
    mb_size = min(config.sgd_minibatch_size, batch_total)
    num_mb = batch_total // mb_size

    def init_fn(seed: int = 0) -> MAState:
        rng = jax.random.PRNGKey(seed)
        rng, k_init, k_env = jax.random.split(rng, 3)
        env_states, obs = ma_vector_reset(env, k_env, G)
        params = module.init(k_init, obs.reshape(flat_n, -1))
        return MAState(params, tx.init(params), env_states, obs, rng,
                       jnp.zeros(G), jnp.zeros(()), jnp.zeros(()))

    def rollout_step(carry, _):
        params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
        rng, k_act, k_step = jax.random.split(rng, 3)
        flat_obs = obs.reshape(flat_n, -1)
        action, logp, value = module.forward_exploration(
            params, flat_obs, k_act)
        actions_gm = action.reshape(G, M)
        env_states, next_obs, rewards, done, _ = ma_vector_step(
            env, env_states, actions_gm, k_step)
        # Episode return: summed team reward per game.
        ep_ret = ep_ret + rewards.sum(axis=-1)
        dsum = dsum + jnp.sum(jnp.where(done, ep_ret, 0.0))
        dcnt = dcnt + jnp.sum(done)
        ep_ret = jnp.where(done, 0.0, ep_ret)
        out = (flat_obs, action, logp, value,
               rewards.reshape(flat_n),
               jnp.repeat(done, M))  # game done → every agent stream
        return (params, env_states, next_obs, rng, ep_ret, dsum, dcnt), out

    def train_step(state: MAState) -> Tuple[MAState, Dict[str, jax.Array]]:
        carry = (state.params, state.env_states, state.obs, state.rng,
                 state.ep_return, state.done_return_sum, state.done_count)
        carry, traj = jax.lax.scan(rollout_step, carry, None, length=T)
        params, env_states, obs, rng, ep_ret, dsum, dcnt = carry
        obs_t, act_t, logp_t, val_t, rew_t, done_t = traj  # [T, G*M, ...]

        _, last_value = module.apply(params, obs.reshape(flat_n, -1))
        adv, vtarg = gae_jax(rew_t, val_t, done_t, last_value,
                             config.gamma, config.lambda_)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        flat = {
            "obs": obs_t.reshape(batch_total, -1),
            "actions": act_t.reshape(batch_total),
            "action_logp": logp_t.reshape(batch_total),
            "advantages": adv.reshape(batch_total),
            "value_targets": vtarg.reshape(batch_total),
        }

        loss_fn = functools.partial(
            ppo_loss, clip_param=config.clip_param,
            vf_clip_param=config.vf_clip_param,
            vf_loss_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff)

        def sgd_epoch(carry, _):
            params, opt_state, rng = carry
            rng, k = jax.random.split(rng)
            perm = jax.random.permutation(k, batch_total)

            def mb_step(carry, idx):
                params, opt_state = carry
                mb = {k_: v[idx] for k_, v in flat.items()}
                (loss, aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, module, mb)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            idxs = perm[: num_mb * mb_size].reshape(num_mb, mb_size)
            (params, opt_state), losses = jax.lax.scan(
                mb_step, (params, opt_state), idxs)
            return (params, opt_state, rng), losses.mean()

        (params, opt_state, rng), losses = jax.lax.scan(
            sgd_epoch, (params, state.opt_state, rng), None,
            length=config.num_sgd_iter)
        new_state = MAState(params, opt_state, env_states, obs, rng,
                            ep_ret, dsum, dcnt)
        metrics = {
            "total_loss": losses.mean(),
            "episode_return_sum": dsum,
            "episode_count": dcnt,
        }
        return new_state, metrics

    # Steps/iter reported as ENV steps (T*G): the agent axis must not
    # inflate throughput accounting (agent steps = env steps * M).
    return module, init_fn, jax.jit(train_step), T * G


class MAPPO(Algorithm):
    _default_config_cls = MAPPOConfig

    def _setup_anakin(self):
        (self.module, init_fn, self._train_step,
         self._steps_per_iter) = make_anakin_mappo(self.config)
        self._anakin_state = init_fn(self.config.seed)

    def _training_step_anakin(self) -> Dict[str, Any]:
        self._anakin_state, metrics = self._train_step(self._anakin_state)
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics = self._episode_counter_metrics(metrics)
        metrics["num_env_steps_sampled_this_iter"] = self._steps_per_iter
        return metrics

    def _setup_actor_mode(self):
        raise NotImplementedError(
            "MAPPO ships anakin-mode (shared policy); independent-policy "
            "multi-agent training uses MultiAgentBatch on the actor stack")
