"""Algorithm base: the Trainable-like driver (reference:
rllib/algorithms/algorithm.py:150 — setup :482, step :744,
save/load_checkpoint :2018,2081)."""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class Algorithm:
    _default_config_cls = None

    def __init__(self, config=None):
        if config is None:
            config = self._default_config_cls()
        self.config = config
        self.iteration = 0
        self._num_env_steps_sampled = 0
        self.setup()

    # Opt-in: algorithms whose anakin step implements the shard_map data
    # mesh set this True (PPO feedforward, IMPALA/APPO).  Fail-closed:
    # any path without the flag REFUSES num_devices rather than silently
    # running single-device while the user believes they are N-way DP.
    _data_mesh_capable = False

    # ---- lifecycle ----
    def setup(self):
        if getattr(self.config, "num_devices", None) is not None \
                and not (self._data_mesh_capable
                         and self.config.mode == "anakin"):
            from ray_tpu.rllib.utils.mesh import reject_data_mesh

            reject_data_mesh(
                self.config,
                f"{type(self).__name__} in {self.config.mode} mode")
        if self.config.mode == "anakin":
            self._setup_anakin()
        else:
            self._setup_actor_mode()

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        if self.config.mode == "anakin":
            metrics = self._training_step_anakin()
        else:
            metrics = self._training_step_actor()
        self.iteration += 1
        self._num_env_steps_sampled += metrics.get(
            "num_env_steps_sampled_this_iter", 0)
        metrics.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled": self._num_env_steps_sampled,
            "time_this_iter_s": time.perf_counter() - t0,
        })
        return metrics

    def stop(self):
        stream = getattr(self, "_stream", None)
        if stream is not None:
            stream.close()
        rb = getattr(self, "_rb", None)
        if rb is not None and hasattr(rb, "close"):
            rb.close()
        workers = getattr(self, "workers", None)
        if workers is not None:
            workers.stop()

    def evaluate(self, num_steps: int = 1000) -> Dict[str, float]:
        """Greedy in-env evaluation (reference: Algorithm.evaluate /
        the `rllib evaluate` CLI).  Default covers anakin algorithms
        whose module speaks the RLModule forward_inference protocol;
        offline algorithms override with their own evaluators."""
        module = getattr(self, "module", None)
        if self.config.mode != "anakin" or module is None \
                or not hasattr(module, "forward_inference"):
            raise NotImplementedError(
                f"{type(self).__name__} has no generic evaluator (needs "
                "anakin mode + an RLModule with forward_inference)")
        import jax

        from ray_tpu.rllib.algorithms.bc import make_greedy_eval_rollout
        from ray_tpu.rllib.env.jax_envs import make_jax_env

        if getattr(self, "_eval_rollout_fn", None) is None:
            try:
                env = make_jax_env(self.config.env) \
                    if isinstance(self.config.env, str) else self.config.env
            except ValueError:
                # e.g. multi-agent env names live in their own registry
                # and speak a different rollout protocol.
                raise NotImplementedError(
                    f"no generic evaluator for env {self.config.env!r} "
                    "(not a single-agent jittable env)") from None
            if getattr(env, "obs_dim", None) is None \
                    and getattr(env, "obs_shape", None) is None:
                raise NotImplementedError(
                    f"env {type(env).__name__} does not speak the "
                    "single-agent jittable protocol")
            self._eval_rollout_fn = make_greedy_eval_rollout(env, module)
            self._eval_rollout_key = jax.random.PRNGKey(
                self.config.seed + 1)
        self._eval_rollout_key, k = jax.random.split(self._eval_rollout_key)
        r = self._eval_rollout_fn(self._anakin_state.params, k, num_steps)
        return {"episode_reward_mean": float(r)}

    # ---- checkpointing (Trainable protocol) ----
    def save_checkpoint(self) -> Checkpoint:
        if self.config.mode == "anakin":
            return Checkpoint.from_pytree(
                self._anakin_state.params,
                extra={"iteration": self.iteration})
        return Checkpoint.from_pytree(self.learner.get_weights(),
                                      extra={"iteration": self.iteration})

    def load_checkpoint(self, checkpoint: Checkpoint):
        params = checkpoint.to_pytree()
        self.iteration = checkpoint.extra().get("iteration", 0)
        if self.config.mode == "anakin":
            self._anakin_state = self._anakin_state._replace(params=params)
        else:
            self.learner.set_weights(params)
            self.workers.sync_weights(params)

    # ---- shared helpers ----
    def _episode_counter_metrics(self, metrics: Dict[str, Any]
                                 ) -> Dict[str, Any]:
        """Convert the cumulative on-device episode counters
        (episode_return_sum/episode_count) into a per-iter
        episode_reward_mean.  Stateful delta tracking shared by the
        replay-family algorithms (DQN, SAC)."""
        prev_sum, prev_cnt = getattr(self, "_prev_counters", (0.0, 0.0))
        cum_sum = metrics.pop("episode_return_sum")
        cum_cnt = metrics.pop("episode_count")
        self._prev_counters = (cum_sum, cum_cnt)
        dsum, dcnt = cum_sum - prev_sum, cum_cnt - prev_cnt
        if dcnt > 0:
            self._ep_reward_ema = dsum / dcnt
        metrics["episode_reward_mean"] = getattr(self, "_ep_reward_ema",
                                                 float("nan"))
        return metrics

    # hooks provided by concrete algorithms
    def _setup_anakin(self):
        raise NotImplementedError(f"{type(self).__name__} has no anakin mode")

    def _setup_actor_mode(self):
        raise NotImplementedError(f"{type(self).__name__} has no actor mode")

    def _training_step_anakin(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _training_step_actor(self) -> Dict[str, Any]:
        raise NotImplementedError
