"""RLlib-equivalent reinforcement learning on TPU (reference: rllib/).

Two execution modes everywhere: `anakin` (envs inside the compiled TPU
program — the throughput path) and `actor` (CPU rollout actors feeding the
mesh learner — the generality path, shaped like the reference)."""
from ray_tpu.rllib.algorithms.algorithm import Algorithm  # noqa: F401
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bandit import (  # noqa: F401
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
)
from ray_tpu.rllib.algorithms.bc import BC, BCConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dyna_q import DynaQ, DynaQConfig  # noqa: F401
from ray_tpu.rllib.algorithms.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.algorithms.qmix import QMix, QMixConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo_ma import MAPPO, MAPPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.algorithms.td3 import DDPG, TD3, DDPGConfig, TD3Config  # noqa: F401
from ray_tpu.rllib.core.learner import JaxLearner, LearnerGroup  # noqa: F401
from ray_tpu.rllib.core.rl_module import (  # noqa: F401
    DiscreteActorCritic,
    RLModuleSpec,
)
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch  # noqa: F401

ALGORITHMS = {"PPO": PPOConfig, "IMPALA": IMPALAConfig, "DQN": DQNConfig,
              "SAC": SACConfig, "BC": BCConfig, "MAPPO": MAPPOConfig,
              "APPO": APPOConfig, "TD3": TD3Config, "DDPG": DDPGConfig,
              "MARWIL": MARWILConfig, "ES": ESConfig,
              "BanditLinUCB": BanditLinUCBConfig,
              "BanditLinTS": BanditLinTSConfig,
              "DynaQ": DynaQConfig, "QMIX": QMixConfig}


def get_algorithm_config(name: str) -> AlgorithmConfig:
    """Registry lookup (reference: rllib/algorithms/registry.py)."""
    if name not in ALGORITHMS:
        raise ValueError(f"unknown algorithm {name!r}; have {list(ALGORITHMS)}")
    return ALGORITHMS[name]()
